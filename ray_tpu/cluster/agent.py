"""Node agent: the per-node runtime (raylet analog).

One process per node, the equivalent of the reference's raylet
(/root/reference/src/ray/raylet/node_manager.h:140): it owns the node's
authoritative resource ledger (grant-or-reject admission,
local_lease_manager.h:39-61), a pool of worker subprocesses
(worker_pool.h), the node's shared-memory object store (the plasma
store runs inside the raylet process — plasma/store_runner.h:28), and
object pulls from remote nodes (pull_manager.h). It heartbeats resource
snapshots to the head (raylet_report_resources_period_milliseconds=100).
"""
from __future__ import annotations

import logging
import os
import pickle
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.scheduler import ResourceRequest, ResourceVocab
from ray_tpu.scheduler.instances import NodeAcceleratorState
from ray_tpu.scheduler.resources import make_ledger

from .pip_env import env_slice, has_env as _has_env

from .common import (
    REPORT_PERIOD_S,
    LeaseRequest,
    NodeInfo,
    NodeReport,
    SealInfo,
    new_id,
)
from .object_plane import (
    CHUNKED_PULLS_INFLIGHT,
    OBJECT_TRANSFER_BYTES,
    PEER_CONN_GRANTED,
    PEER_CONN_REUSED,
    PEER_CONN_REVOKED,
    TRANSFER_CHUNK_MS,
    TRANSFER_STRIPE_MS,
    ChunkFetchError,
    fetch_chunked,
)
from .rpc import (
    HANDLER_STATS,
    RpcClient,
    RpcError,
    RpcNotLeaderError,
    RpcServer,
    RpcStaleEpochError,
)
from .zygote import ZygoteClient, fork_available


from ray_tpu.config import cfg
from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Gauge as _Gauge
from ray_tpu.util.metrics import Histogram as _Histogram

logger = logging.getLogger("ray_tpu.cluster.agent")

_EPS = 1e-9

# worker-lifecycle instruments (worker_pool.cc stats analog). Process-wide
# like every metric in util.metrics; per-agent counts live in
# NodeAgent.pool_stats and surface through DebugState.
WORKER_SPAWN_MS = _Histogram(
    "worker_spawn_ms",
    "Worker spawn-to-register latency; path=fork (zygote) vs spawn (cold).",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000),
    label_names=("path",),
)
WORKER_POOL_HITS = _Counter(
    "worker_pool_hits_total",
    "Lease dispatches served immediately by an idle pooled worker.",
)
WORKER_POOL_MISSES = _Counter(
    "worker_pool_misses_total",
    "Lease dispatches that found the idle pool empty and had to wait.",
)
WORKER_PRESTART_INFLIGHT = _Gauge(
    "worker_prestart_inflight",
    "Prestarted workers spawned on a head hint, not yet registered.",
)


class _MemStore:
    """Fallback object store when the native shm arena can't build."""

    def __init__(self):
        self._data: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put_bytes(self, oid: str, data: bytes) -> None:
        with self._lock:
            self._data[oid] = data

    def get_bytes(self, oid: str) -> bytes:
        with self._lock:
            return self._data[oid]

    def get_range(self, oid: str, offset: int, length: int) -> bytes:
        with self._lock:
            return self._data[oid][offset : offset + length]

    def object_size(self, oid: str) -> int:
        with self._lock:
            return len(self._data[oid])

    def contains(self, oid: str) -> bool:
        with self._lock:
            return oid in self._data

    def delete(self, oid: str) -> None:
        with self._lock:
            self._data.pop(oid, None)

    def close(self, unlink: bool = False) -> None:
        self._data.clear()


class _ClassedAdmission:
    """Priority admission over N transfer slots — the object-plane QoS of
    the reference's PullManager/PushManager (pull_manager.h:40-47 GET >
    WAIT > TASK_ARGS classes; push_manager.h:28-36 in-flight cap): a
    waiting higher class always gets the next free slot, so a storm of
    task-arg transfers cannot starve an interactive get.

    Scope note (push side): the slot covers the store read + reply
    construction, not the kernel's socket send that happens after the
    handler returns — the enforced property is priority ORDERING of
    admissions plus a bound on concurrently materialized replies, an
    approximation of the reference's chunked in-flight cap.

    ``timeout``: a bounded wait keeps a storm from parking the RPC
    server's whole thread pool forever — on expiry the transfer errors
    and the requester retries through its locate loop."""

    PRIO = {"get": 0, "wait": 1, "task_args": 2}

    def __init__(self, slots: int, timeout: Optional[float] = None):
        self._slots = max(1, int(slots))
        self._timeout = timeout
        self._cv = threading.Condition()
        self._in_flight = 0
        self._waiting = [0, 0, 0]

    def __call__(self, purpose: str):
        return _AdmissionSlot(self, self.PRIO.get(purpose, 2))


class _AdmissionSlot:
    __slots__ = ("_adm", "_prio")

    def __init__(self, adm: _ClassedAdmission, prio: int):
        self._adm = adm
        self._prio = prio

    def __enter__(self):
        adm, p = self._adm, self._prio
        deadline = (
            None
            if adm._timeout is None
            else time.monotonic() + adm._timeout
        )
        with adm._cv:
            adm._waiting[p] += 1
            try:
                while adm._in_flight >= adm._slots or any(
                    adm._waiting[q] for q in range(p)
                ):
                    if (
                        deadline is not None
                        and time.monotonic() >= deadline
                    ):
                        raise TimeoutError(
                            "transfer admission timed out "
                            f"(class={p}, slots={adm._slots})"
                        )
                    adm._cv.wait(timeout=1.0)
            finally:
                adm._waiting[p] -= 1
            adm._in_flight += 1
        return self

    def __exit__(self, *exc):
        adm = self._adm
        with adm._cv:
            adm._in_flight -= 1
            adm._cv.notify_all()
        return False


class _WorkerHandle:
    def __init__(self, worker_id: str, proc):
        self.worker_id = worker_id
        self.proc = proc  # subprocess.Popen or zygote.ForkedProc
        self.client: Optional[RpcClient] = None
        self.ready = threading.Event()
        self.actor_id: Optional[str] = None  # pinned for an actor
        self.lease_id: Optional[str] = None  # pinned for a task lease
        self.pip_key: Optional[str] = None  # bound to a pip runtime env
        self.idle_since: float = 0.0  # env workers: reap when idle long
        self.lock = threading.Lock()  # serializes pushes (actor ordering)
        self.spawned_at: float = 0.0  # monotonic spawn time (spawn_ms metric)
        self.spawn_path: str = "spawn"  # "fork" (zygote) | "spawn" (cold)
        self.spawn_pending: bool = False  # spawned, not yet registered
        self.prestart_pending: bool = False  # head-hinted, not yet registered
        # actor creation applied a persisted runtime env here: reuse denied
        self.env_tainted: bool = False
        # task_id -> dispatch time of in-flight plain tasks (OOM victim
        # selection: the memory monitor kills the NEWEST task first)
        self.running: Dict[str, float] = {}


class NodeAgent:
    def __init__(
        self,
        head_address: str,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
        host: str = "127.0.0.1",
        num_workers: Optional[int] = None,
        store_capacity: int = 1 << 28,
        node_id: Optional[str] = None,
    ):
        self.node_id = node_id or new_id()
        self.head_address = head_address
        self.head = RpcClient(head_address)
        self.vocab = ResourceVocab()
        self.ledger = make_ledger(self.vocab, resources)
        # chip-index assignment on top of the scalar ledger: granted leases
        # carry TPU_VISIBLE_CHIPS / CUDA_VISIBLE_DEVICES
        self.accel = NodeAcceleratorState(resources)
        self.resources = dict(resources)
        self.labels = dict(labels or {})
        self._lock = threading.RLock()
        self._shutdown = False
        # set for real from the RegisterNode reply below; None (unstamped,
        # always accepted) until then so reporter threads that start early
        # never race the registration round-trip
        self._head_epoch: Optional[int] = None

        # --- object store (plasma-in-raylet analog), wrapped with LRU
        # disk spill + restore so a full arena backpressures to disk
        # instead of erroring (eviction_policy.h / local_object_manager.h)
        # paths carry the pid: a node id can be reused across cluster
        # incarnations (tests, restarts), and a lingering agent from an old
        # incarnation must never share an arena or spill dir with a new one
        self.store_path = os.path.join(
            tempfile.gettempdir(),
            f"ray_tpu_store_{self.node_id}_{os.getpid()}.shm",
        )
        try:
            # a killed agent (chaos kill tier) never reaches the unlink in
            # shutdown(): sweep arenas/spill dirs whose owning pid is dead
            # so /tmp does not accrete one orphaned arena per kill
            from ray_tpu.native.shm_store import sweep_orphan_stores

            swept = sweep_orphan_stores()
            if swept:
                logger.info("swept %d orphaned store files", len(swept))
        except Exception:  # noqa: BLE001 - hygiene, never fatal
            logger.debug("orphan store sweep failed", exc_info=True)
        try:
            # same hygiene for DAG/pipeline ring files: a SIGKILLed
            # producer or consumer never reaches its unlink
            from ray_tpu.dag.channel import sweep_orphan_rings

            swept = sweep_orphan_rings()
            if swept:
                logger.info("swept %d orphaned ring files", len(swept))
        except Exception:  # noqa: BLE001 - hygiene, never fatal
            logger.debug("orphan ring sweep failed", exc_info=True)
        try:
            # and for data-plane endpoint sidecars (transport.py): a
            # SIGKILLed agent never unlinks its own .ep file
            from ray_tpu.native.net import sweep_orphan_endpoints

            swept = sweep_orphan_endpoints()
            if swept:
                logger.info("swept %d orphaned net endpoints", len(swept))
        except Exception:  # noqa: BLE001 - hygiene, never fatal
            logger.debug("orphan endpoint sweep failed", exc_info=True)
        try:
            # and for dark-plane counter pages (native/counters.py)
            from ray_tpu.native.counters import sweep_orphan_counters

            swept = sweep_orphan_counters()
            if swept:
                logger.info("swept %d orphaned counter pages", swept)
        except Exception:  # noqa: BLE001 - hygiene, never fatal
            logger.debug("orphan counter sweep failed", exc_info=True)
        try:
            from ray_tpu.native import NativeObjectStore

            inner = NativeObjectStore(
                path=self.store_path, capacity=store_capacity
            )
        except Exception:  # noqa: BLE001 - toolchain missing
            logger.warning("native store unavailable; using in-memory store")
            inner = _MemStore()
            self.store_path = ""
        from ray_tpu.native.spill import SpillingStore
        from ray_tpu.native.spill_storage import storage_from_uri

        spill_dir = os.path.join(
            tempfile.gettempdir(),
            f"ray_tpu_spill_{self.node_id}_{os.getpid()}",
        )
        self.store = SpillingStore(
            inner,
            spill_dir=spill_dir,
            capacity=store_capacity,
            # remote spill (external_storage.py analog): file:// (default)
            # | memory:// | s3://bucket/prefix
            backend=storage_from_uri(cfg.spill_storage_uri, spill_dir),
        )

        # --- bundle (placement group) reservations ---
        # pg_id -> {"state": prepared|committed, "bundles": {idx: avail_map}}
        self._bundles: Dict[str, dict] = {}

        # --- RPC surface ---
        handlers = {
            "ExecuteLease": self._h_execute_lease,
            "ExecuteLeaseBatch": self._h_execute_lease_batch,
            "StoreObject": self._h_store_object,
            "FetchObject": self._h_fetch_object,
            "FetchObjectBatch": self._h_fetch_object_batch,
            "FetchObjectMeta": self._h_fetch_object_meta,
            "FetchObjectChunk": self._h_fetch_object_chunk,
            "DeleteObjects": self._h_delete_objects,
            "GetObjectForWorker": self._h_get_object_for_worker,
            "WorkerPut": self._h_worker_put,
            "WorkerSealed": self._h_worker_sealed,
            "StreamConsumed": self._h_stream_consumed,
            "RegisterWorker": self._h_register_worker,
            "TaskDone": self._h_task_done,
            "TaskDoneBatch": lambda reqs: [
                self._h_task_done(r) for r in reqs
            ],
            "RefUpdate": self._h_ref_update,
            "PrepareBundles": self._h_prepare_bundles,
            "CommitBundles": self._h_commit_bundles,
            "RollbackBundles": self._h_rollback_bundles,
            "ReturnBundles": self._h_return_bundles,
            "KillActor": self._h_kill_actor,
            "PrestartWorkers": self._h_prestart_workers,
            "ActorWorkerAddress": self._h_actor_worker_address,
            "ReturnWorkerLease": self._h_return_worker_lease,
            "CancelLease": self._h_cancel_lease,
            "DagInstall": lambda r: self._forward_to_actor_worker(
                "DagInstall", r
            ),
            "DagTeardown": lambda r: self._forward_to_actor_worker(
                "DagTeardown", r
            ),
            "PipelineInstall": lambda r: self._forward_to_actor_worker(
                "PipelineInstall", r
            ),
            "PipelineTeardown": lambda r: self._forward_to_actor_worker(
                "PipelineTeardown", r
            ),
            "Shutdown": self._h_shutdown,
            "DebugState": self._h_debug_state,
            "ServeStats": self._h_serve_stats,
            "RevokePeerLink": self._h_revoke_peer_link,
            "ChaosKillZygote": self._h_chaos_kill_zygote,
            "ChaosDropPeerConn": self._h_chaos_drop_peer_conn,
            "Ping": lambda r: "pong",
        }
        # serving-plane stats pushed by co-located replica workers
        # (node-local control traffic): pid -> {deployment, stats, ts}
        self._serve_stats: Dict[int, dict] = {}
        self._server = RpcServer(handlers, host=host, port=0)
        self.address = self._server.address

        # --- worker pool (worker_pool.h analog) ---
        if num_workers is None:
            num_workers = max(2, min(int(resources.get("CPU", 2)), 8))
        self._workers: Dict[str, _WorkerHandle] = {}
        self._idle: List[str] = []
        self._idle_cv = threading.Condition(self._lock)
        self._actor_workers: Dict[str, str] = {}  # actor_id -> worker_id
        # task leases held by this node's workers (worker_lease grants):
        # lease_id -> {worker_id, alloc, owner, granted_at}. The lease pins
        # its worker out of the idle pool like an actor does, and the pool
        # backfills 1:1 for the same reason.
        self._task_leases: Dict[str, dict] = {}
        self._lease_stats: Dict[str, int] = {
            "granted": 0,
            "returned": 0,
            "lost": 0,
        }
        self._actor_meta: Dict[str, dict] = {}  # actor_id -> {name, max_restarts}
        self._actor_allocs: Dict[str, Any] = {}  # actor_id -> held lease alloc
        self._actor_fifo: Dict[str, list] = {}  # actor_id -> ordered methods
        self._actor_draining: set = set()
        self._async_actors: set = set()  # actor_ids multiplexing on a loop
        # async-actor methods accepted by a worker, completion pending
        # (worker reports via TaskDone): task_id -> (spec, worker handle)
        self._async_pending: Dict[str, tuple] = {}
        # TaskDone replies that arrived before their PushTask reply did
        self._early_task_done: Dict[str, dict] = {}
        # per-async-actor push coalescing (see _drain_async_methods)
        self._async_buf: Dict[str, deque] = {}
        self._async_draining: set = set()
        self._num_workers = num_workers
        # pool observability (DebugState "pool"): per-agent counts behind
        # the process-wide Prometheus instruments above
        self.pool_stats: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "reused": 0,
            "forked": 0,
            "cold_spawned": 0,
        }
        self._prestart_inflight = 0
        # head-signalled drain-ahead (PR 19): while retiring, don't warm
        # the pool — new work is steered elsewhere and any prestarted
        # worker would die with the node
        self._draining = False
        # ALL spawns not yet registered (prestarted or not): the backfill
        # and prestart sizing both count these as future-free capacity, so
        # N concurrent creations cannot each trigger their own spawn for
        # the same hole (the overspawn burned ~100ms of fork+init CPU per
        # duplicate on a loaded host)
        self._spawns_pending = 0
        # fork-server: one zygote pays the worker import graph once; new
        # workers fork from it in milliseconds (cold spawn stays the
        # fallback — see zygote.py)
        self._zygote: Optional[ZygoteClient] = None
        self._zygote_restarts = 0
        if cfg.fork_server and fork_available():
            self._start_zygote()
        # initial pool fill happens OFF the construction path: the first
        # fork blocks on the zygote's one-time import warmup (~seconds
        # with jax), and head registration must not wait behind it —
        # leases arriving early just park in _pop_idle_worker meanwhile
        threading.Thread(
            target=self._fill_pool, name="agent-pool-fill", daemon=True
        ).start()

        # remote-fetch client cache (peer addresses come from head lookups)
        self._peer_clients: Dict[str, RpcClient] = {}
        # pull admission (push_manager.h / pull_manager.h analog): bound
        # concurrent inbound transfers, and coalesce concurrent pulls of
        # ONE object into a single fetch (broadcast of a big object to N
        # workers on this node = one wire transfer, not N)
        self._pull_adm = _ClassedAdmission(cfg.max_concurrent_pulls)
        # outbound (serving) side: bound concurrent transfers shipped to
        # peers/clients, same GET > WAIT > TASK_ARGS classes; bounded wait
        # so a fetch storm can't park the RPC thread pool forever
        self._push_adm = _ClassedAdmission(
            cfg.max_concurrent_pushes, timeout=60.0
        )
        self._pull_waiters: Dict[str, threading.Event] = {}

        # --- cross-node data plane (transport.py): stripe server beside
        # the RPC server + the peer-link cache (head-granted connection
        # leases). The per-incarnation auth token never leaves memory
        # except inside grant replies; a fresh token per agent process
        # means stale cached links die at the handshake and re-grant.
        import secrets

        from .transport import PeerLinkCache

        self.net_token = secrets.token_hex(16)
        self._data_server = None
        if cfg.native_net:
            try:
                from .transport import DataPlaneServer

                self._data_server = DataPlaneServer(
                    self.store,
                    self.node_id,
                    self.net_token,
                    epoch_fn=lambda: self._head_epoch,
                    admission=self._push_adm,
                    host=host,
                )
            except Exception:  # noqa: BLE001 - chunked RPC still serves
                logger.exception(
                    "data-plane server failed to start; peers fall back "
                    "to chunked RPC"
                )
        self._links = PeerLinkCache(self._grant_peer_link)

        # IO-bound pool: threads mostly park on worker RPCs. Sized well past
        # the worker count so async-actor methods (which each hold a thread
        # while multiplexing on the worker's event loop) can overlap deeply.
        self._exec_pool = ThreadPoolExecutor(
            max_workers=num_workers + 32,
            thread_name_prefix=f"agent-{self.node_id[:6]}",
        )

        # memory-pressure monitor (pressure_memory_monitor.h analog): when
        # host memory usage crosses the threshold, kill the worker running
        # the NEWEST plain task (its lease retries; earlier work survives)
        self.metrics_oom_kills = 0
        if cfg.memory_monitor_interval_s > 0:
            threading.Thread(
                target=self._memory_monitor_loop,
                name="agent-memmon",
                daemon=True,
            ).start()

        # metrics federation (ISSUE 15): this agent's registry ships as
        # typed deltas on the coalesced head report at
        # cfg.metrics_interval_s cadence; workers' deltas (relayed via
        # WorkerSealed) queue here pre-labeled and ride the same report
        from ray_tpu.util.metrics import DeltaExporter

        self._metric_exporter = DeltaExporter()
        self._metric_lock = threading.Lock()
        self._worker_metric_relays: List[Dict[str, Any]] = []
        self._metrics_last_ship = 0.0

        # coalescing completion/seal reporter (see _reporter_loop)
        self._report_queue: List[Dict[str, Any]] = []
        self._report_cv = threading.Condition()
        threading.Thread(
            target=self._reporter_loop, name="agent-reporter", daemon=True
        ).start()
        # plain-task batch dispatcher (see _task_drain_loop)
        self._task_buf: deque = deque()
        self._task_cv = threading.Condition()
        threading.Thread(
            target=self._task_drain_loop, name="agent-task-drain", daemon=True
        ).start()
        # pip runtime environments (reference runtime_env pip/uv builders):
        # dedicated workers per env key, reaped after idle timeout
        from .pip_env import PipEnvManager

        # per-agent base dir: GC liveness is tracked by THIS agent's
        # refcounts, so the directory must not be shared with other
        # agents on the host (each simulated node is its own "machine")
        self._pip_mgr = PipEnvManager(
            os.path.join(
                os.environ.get("RAY_TPU_PIP_ENV_BASE", "")
                or os.path.join(tempfile.gettempdir(), "ray_tpu_pip_envs"),
                self.node_id,
            )
        )
        self._pip_idle: Dict[str, List[str]] = {}
        threading.Thread(
            target=self._pip_gc_loop, name="agent-pipgc", daemon=True
        ).start()

        # dependency-waiting leases (see _dep_loop)
        self._dep_waiting: Dict[str, tuple] = {}  # task_id -> (spec, missing)
        self._dep_cv = threading.Condition()
        # ids fetchable from the head without store locality (inline/error)
        self._dep_ready_ids: set = set()
        self._pulls_in_flight: set = set()
        threading.Thread(
            target=self._dep_loop, name="agent-deps", daemon=True
        ).start()

        reply = self.head.call(
            "RegisterNode",
            self._node_info(),
            retries=30,
            retry_interval=0.2,
        )
        assert reply["node_id"] == self.node_id
        # cluster epoch adopted at registration: control RPCs to the head
        # are stamped with it, so a rebuilt head fences this agent out the
        # moment it restarts — until the agent re-registers (the resync
        # protocol) and adopts the new epoch
        self._head_epoch = reply.get("epoch")
        self._report_thread = threading.Thread(
            target=self._report_loop, name="agent-report", daemon=True
        )
        self._report_thread.start()

    # ------------------------------------------------------------------
    # worker pool
    # ------------------------------------------------------------------
    def _fill_pool(self) -> None:
        for _ in range(self._num_workers):
            if self._shutdown:
                return
            try:
                self._spawn_worker()
            except Exception:  # noqa: BLE001 - report loop backfills later
                logger.exception("initial worker spawn failed")

    def _start_zygote(self) -> None:
        env = dict(os.environ)
        env["RAY_TPU_HEAD_ADDRESS"] = self.head_address
        env["RAY_TPU_NODE_ID"] = self.node_id
        try:
            self._zygote = ZygoteClient(self.address, self.store_path, env)
        except OSError:
            logger.exception("zygote start failed; using cold spawn")
            self._zygote = None

    def _zygote_for_fork(self) -> Optional[ZygoteClient]:
        """Live zygote client, restarting a broken one (bounded) —
        repeated breakage means fork doesn't work here; stop trying."""
        z = self._zygote
        if z is None or not z.broken:
            return z
        with self._lock:
            if self._zygote is z:
                z.close()
                self._zygote_restarts += 1
                if self._zygote_restarts > 3:
                    logger.warning(
                        "zygote broke %d times; cold spawn from now on",
                        self._zygote_restarts,
                    )
                    self._zygote = None
                else:
                    self._start_zygote()
            return self._zygote

    def _h_prestart_workers(self, req: dict) -> dict:
        """Head hint: N actor-creation leases are headed here — warm the
        pool while they are in flight (worker_pool.cc PrestartWorkers).
        Bounded by prestart_max_workers above the steady pool size, and
        discounted by workers already idle or warming."""
        want = int(req.get("count", 0))
        if want <= 0 or self._shutdown or self._draining:
            return {"spawned": 0}
        with self._idle_cv:
            free = len(self._idle) + self._spawns_pending
            cap = (
                self._num_workers
                + cfg.prestart_max_workers
                - len(self._workers)
            )
        # target: enough warm capacity for every inbound creation AND a
        # full free pool after they pin — the creations' 1:1 backfills
        # would spawn the same workers anyway, just later (trailing the
        # churn instead of overlapping the leases' flight time)
        n = min(max(0, want + self._num_workers - free), max(0, cap))
        spawned = 0
        for _ in range(n):
            try:
                self._spawn_worker(prestart=True)
                spawned += 1
            except Exception:  # noqa: BLE001 - fork pressure
                logger.exception("prestart spawn failed")
                break
        return {"spawned": spawned}

    def _spawn_worker(
        self, pip_env: Optional[Tuple] = None, prestart: bool = False
    ) -> _WorkerHandle:
        worker_id = new_id()
        t0 = time.monotonic()
        if pip_env is None:
            # fast path: fork from the warm zygote (ms) instead of a cold
            # interpreter + import (seconds). Env-bound workers keep the
            # cold path: their interpreter/sys.path differ by design.
            zc = self._zygote_for_fork()
            if zc is not None:
                forked = zc.fork_worker(worker_id)
                if forked is not None:
                    handle = _WorkerHandle(worker_id, forked)
                    handle.spawned_at = t0
                    handle.spawn_path = "fork"
                    return self._track_spawn(handle, prestart)
        env = dict(os.environ)
        env["RAY_TPU_HEAD_ADDRESS"] = self.head_address
        env["RAY_TPU_NODE_ID"] = self.node_id
        interpreter = sys.executable
        if pip_env is not None:
            kind = pip_env[2] if len(pip_env) > 2 else "pip"
            if kind == "conda":
                # conda envs bring their own interpreter (pip_env.py) and
                # must have ray_tpu importable inside them — reference
                # conda.py injects ray into the env's dependencies the
                # same way. RAY_TPU_CONDA_INJECT_SOURCE=1 opts into
                # prepending this source checkout's parent dir instead
                # (dev convenience only: PYTHONPATH entries shadow the
                # env's own site-packages, defeating isolation for any
                # package both provide).
                from .pip_env import PipEnvManager

                interpreter = PipEnvManager.interpreter_for(kind, pip_env[1])
                if os.environ.get("RAY_TPU_CONDA_INJECT_SOURCE"):
                    env["PYTHONPATH"] = (
                        os.path.dirname(
                            os.path.dirname(os.path.dirname(__file__))
                        )
                        + os.pathsep
                        + env.get("PYTHONPATH", "")
                    )
            else:
                # pip/uv --target env: the worker prepends this dir to
                # sys.path at startup, shadowing base site-packages
                env["RAY_TPU_PIP_ENV_DIR"] = pip_env[1]
        proc = subprocess.Popen(
            [
                interpreter,
                "-m",
                "ray_tpu.cluster.worker",
                "--agent",
                self.address,
                "--worker-id",
                worker_id,
                "--store",
                self.store_path,
            ],
            env=env,
        )
        handle = _WorkerHandle(worker_id, proc)
        handle.spawned_at = t0
        if pip_env is not None:
            handle.pip_key = pip_env[0]
        return self._track_spawn(handle, prestart)

    def _track_spawn(
        self, handle: _WorkerHandle, prestart: bool
    ) -> _WorkerHandle:
        self.pool_stats[
            "forked" if handle.spawn_path == "fork" else "cold_spawned"
        ] += 1
        with self._idle_cv:
            if prestart:
                handle.prestart_pending = True
                self._prestart_inflight += 1
                WORKER_PRESTART_INFLIGHT.inc()
            if handle.pip_key is None:
                # pip-bound workers register into _pip_idle, never the
                # plain pool — counting them here would let an env build
                # storm suppress plain-worker backfill
                handle.spawn_pending = True
                self._spawns_pending += 1
            self._workers[handle.worker_id] = handle
        return handle

    def _prestart_done_locked(self, handle: _WorkerHandle) -> None:
        """Clear spawn/prestart reservations exactly once (register or
        death). Caller holds self._idle_cv."""
        if handle.spawn_pending:
            handle.spawn_pending = False
            self._spawns_pending -= 1
        if handle.prestart_pending:
            handle.prestart_pending = False
            self._prestart_inflight -= 1
            WORKER_PRESTART_INFLIGHT.dec()

    def _h_register_worker(self, req: dict) -> dict:
        # channel construction stays OUTSIDE the idle lock: a burst of
        # registrations (prestart landing) must not serialize grpc
        # channel setup under the lock every _pop_idle_worker needs
        client = RpcClient(req["address"])
        with self._idle_cv:
            handle = self._workers.get(req["worker_id"])
            if handle is None:
                client.close()
                return {"ok": False}
            handle.client = client
            handle.ready.set()
            handle.idle_since = time.monotonic()
            self._prestart_done_locked(handle)
            if handle.spawned_at:
                WORKER_SPAWN_MS.observe(
                    (time.monotonic() - handle.spawned_at) * 1000.0,
                    labels={"path": handle.spawn_path},
                )
                handle.spawned_at = 0.0
            if handle.pip_key is not None:
                handle.idle_since = time.monotonic()
                self._pip_idle.setdefault(handle.pip_key, []).append(
                    handle.worker_id
                )
            else:
                self._idle.append(handle.worker_id)
            self._idle_cv.notify_all()
        return {"ok": True, "node_id": self.node_id}

    def _pop_idle_worker(self, timeout: float = 60.0) -> Optional[_WorkerHandle]:
        deadline = time.monotonic() + timeout
        with self._idle_cv:
            if self._idle:
                self.pool_stats["hits"] += 1
                WORKER_POOL_HITS.inc()
                return self._workers[self._idle.pop()]
            self.pool_stats["misses"] += 1
            WORKER_POOL_MISSES.inc()
            while not self._idle:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    return None
                self._idle_cv.wait(timeout=min(remaining, 0.5))
            return self._workers[self._idle.pop()]

    @staticmethod
    def _close_worker_client(handle: _WorkerHandle) -> None:
        """Release a dead/reaped worker's channel (and its breaker-registry
        hold — worker ports are ephemeral, so leaving these behind grows
        process state with every churn cycle)."""
        if handle.client is not None:
            try:
                handle.client.close()
            except Exception:  # noqa: BLE001 - already torn down
                pass

    def _return_worker(self, handle: _WorkerHandle) -> None:
        with self._idle_cv:
            if (
                handle.actor_id is None
                and handle.lease_id is None
                and handle.worker_id in self._workers
            ):
                handle.idle_since = time.monotonic()
                if handle.pip_key is not None:
                    self._pip_idle.setdefault(handle.pip_key, []).append(
                        handle.worker_id
                    )
                else:
                    self._idle.append(handle.worker_id)
                self._idle_cv.notify_all()

    def _on_worker_death(self, handle: _WorkerHandle, running: List[LeaseRequest]) -> None:
        """A worker process died (socket/process detection in worker_pool.cc)."""
        running = list(running)
        with self._idle_cv:
            # death can be observed concurrently (failed RPC + health
            # sweep): the pop result marks the FIRST observer, which alone
            # releases once-only state like the pip env refcount
            first = self._workers.pop(handle.worker_id, None) is not None
            if first:
                self._prestart_done_locked(handle)
            if handle.worker_id in self._idle:
                self._idle.remove(handle.worker_id)
            if handle.pip_key is not None:
                lst = self._pip_idle.get(handle.pip_key)
                if lst and handle.worker_id in lst:
                    lst.remove(handle.worker_id)
                if first:
                    self._pip_mgr.release(handle.pip_key)
            # async methods awaiting a TaskDone from this worker die with it
            for tid in [
                t for t, (_, h) in self._async_pending.items() if h is handle
            ]:
                running.append(self._async_pending.pop(tid)[0])
            actor_id = handle.actor_id
            if actor_id:
                self._drop_actor_state(actor_id)
            lease_id = handle.lease_id
            lease_entry = None
            if lease_id:
                handle.lease_id = None
                lease_entry = self._task_leases.pop(lease_id, None)
                if lease_entry is not None:
                    self._lease_stats["lost"] += 1
        try:
            handle.proc.kill()
        except OSError:
            pass
        self._close_worker_client(handle)
        # zombie-pin reclamation: replay the dead reader's view-pin log and
        # release what its finalizers never could (SIGKILL). Waits briefly
        # for the process to be truly gone first — replaying while a
        # half-dead worker's finalizer races its own release could
        # double-release a share (the log's R-before-release ordering
        # protects every other interleaving).
        pid = getattr(handle.proc, "pid", None)
        if pid and self.store_path:
            deadline = time.monotonic() + 1.0
            while handle.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.01)
            if handle.proc.poll() is None:
                # not confirmed dead (D-state under memory pressure):
                # replaying now could double-release against the live
                # process's own finalizer. Leak the pins instead — the
                # arena restart sweep reclaims them.
                logger.warning(
                    "worker %s (pid %d) not reaped within 1s; skipping "
                    "pin-log replay (pins reclaimed at arena restart)",
                    handle.worker_id[:8],
                    pid,
                )
                pid = None
        if pid and self.store_path:
            try:
                released = self.store.release_dead_pins(pid)
                if released:
                    logger.info(
                        "released %d arena view pins leaked by dead "
                        "worker %s (pid %d)",
                        released,
                        handle.worker_id[:8],
                        pid,
                    )
            except Exception:  # noqa: BLE001 - reclamation is best-effort
                logger.debug("pin-log replay failed", exc_info=True)
        if lease_entry is not None:
            self._release(lease_entry["alloc"])
        report: Dict[str, Any] = {"node_id": self.node_id}
        if lease_id and lease_entry is not None:
            # the owner's channel discovers the death by RPC failure and
            # spills its queue; this report lets the head drop the lease
            # from its table (revoked) without waiting for TTL expiry
            report["task_leases"] = [
                {
                    "lease_id": lease_id,
                    "ok": False,
                    "reason": "worker died",
                    "lost": True,
                }
            ]
        # the dead process's holder counts die with it
        report["holders_gone"] = [handle.worker_id]
        if actor_id:
            report["actors_dead"] = [
                {"actor_id": actor_id, "reason": "worker process died"}
            ]
        if running:
            report["failed"] = [
                {
                    "task_id": s.task_id,
                    "reason": f"worker died running {s.name}",
                    "retryable": s.kind == "task",
                }
                for s in running
            ]
        self._report_to_head(report)
        if not self._shutdown and len(self._workers) < self._num_workers:
            self._spawn_worker()

    # ------------------------------------------------------------------
    # lease admission + execution
    # ------------------------------------------------------------------
    def _h_execute_lease_batch(self, specs: List[LeaseRequest]) -> dict:
        """Batched grant-or-reject admission: one RPC per scheduling round
        per node instead of one per lease (the reference amortizes this with
        lease pipelining, normal_task_submitter pipelining; a batched
        scheduler makes the whole round one message)."""
        statuses = [self._h_execute_lease(s)["status"] for s in specs]
        out: Dict[str, Any] = {"statuses": statuses}
        if "reject" in statuses:
            out["available"] = self.ledger.avail_map()
        return out

    def _h_execute_lease(self, spec: LeaseRequest) -> dict:
        req = ResourceRequest.from_map(self.vocab, spec.resources)
        if spec.kind == "actor_method":
            with self._lock:
                worker_id = self._actor_workers.get(spec.actor_id)
                handle = self._workers.get(worker_id) if worker_id else None
                if handle is None:
                    return {
                        "status": "reject",
                        "available": self.ledger.avail_map(),
                    }
                if spec.actor_id in self._async_actors:
                    # asyncio actor: methods multiplex on the worker's event
                    # loop — no FIFO. Pushes coalesce per actor: everything
                    # queued while the previous PushTaskBatch was in flight
                    # rides the next one.
                    self._async_buf.setdefault(spec.actor_id, deque()).append(
                        spec
                    )
                    if spec.actor_id not in self._async_draining:
                        self._async_draining.add(spec.actor_id)
                        self._exec_pool.submit(
                            self._drain_async_methods, spec.actor_id
                        )
                    return {"status": "granted"}
                # per-actor FIFO: the pool must not reorder method calls
                fifo = self._actor_fifo.setdefault(spec.actor_id, [])
                fifo.append(spec)
                if spec.actor_id in self._actor_draining:
                    return {"status": "granted"}
                self._actor_draining.add(spec.actor_id)
            self._exec_pool.submit(self._drain_actor_fifo, spec.actor_id)
            return {"status": "granted"}
        if spec.kind == "worker_lease":
            # task-lease grant: allocate the shape ONCE and pin one worker
            # for the owner's direct dispatch; grant-or-reject against the
            # authoritative ledger like any lease. Worker pinning happens
            # off the admission thread (it can wait on the pool).
            if not self.ledger.try_allocate(req):
                return {
                    "status": "reject",
                    "available": self.ledger.avail_map(),
                }
            assign = self.accel.allocate(spec.resources)
            if assign is None:
                self.ledger.release(req)
                return {
                    "status": "reject",
                    "available": self.ledger.avail_map(),
                }
            self._exec_pool.submit(
                self._activate_task_lease, spec, ("ledger", req, assign)
            )
            return {"status": "granted"}
        if spec.kind == "task" and spec.deps and not self._args_ready(spec):
            # dependency-aware dispatch: wait for args BEFORE taking
            # resources or a worker (lease_dependency_manager.h:41-53) —
            # a ready lease interleaves past this one
            self._park_for_deps(spec)
            return {"status": "granted"}
        if spec.pg_reservation is not None:
            if not self._bundle_allocate(spec.pg_reservation, spec.resources):
                return {"status": "reject", "available": self.ledger.avail_map()}
            scalar_alloc = ("pg", spec.pg_reservation, dict(spec.resources))
        elif self.ledger.try_allocate(req):
            scalar_alloc = ("ledger", req)
        else:
            # stale head view → reject with the authoritative snapshot
            return {"status": "reject", "available": self.ledger.avail_map()}
        # chip-index assignment (resource_instance_set.h analog): a
        # scalar-feasible integer demand always fits; fractional shares can
        # hit fragmentation → undo the scalar grant and reject
        assign = self.accel.allocate(spec.resources)
        if assign is None:
            self._release(scalar_alloc)
            return {"status": "reject", "available": self.ledger.avail_map()}
        alloc = scalar_alloc + (assign,)
        if _has_env(spec.runtime_env):
            # pip/uv/conda runtime env: needs a worker bound to the built
            # env (dedicated interpreter path); dispatched individually —
            # env builds can take seconds and must not stall the batch
            # drainer
            self._exec_pool.submit(self._dispatch_pip_task, spec, alloc)
        elif spec.kind == "actor_creation":
            # pins its worker for life — dispatched individually
            self._exec_pool.submit(self._dispatch_to_worker, spec, alloc)
        else:
            # plain tasks queue for the batching drainer: one PushTaskBatch
            # RPC carries several tasks to one worker (amortizes the
            # per-push round trip the way the reference pipelines leases)
            with self._task_cv:
                self._task_buf.append((spec, alloc))
                self._task_cv.notify()
        return {"status": "granted"}

    # ------------------------------------------------------------------
    # dependency-aware dispatch (LeaseDependencyManager analog,
    # raylet/lease_dependency_manager.h:41-53): a lease whose args are not
    # yet fetchable waits here WITHOUT resources or a worker — a ready
    # lease interleaves past it. Missing remote args are prefetched into
    # the local store while waiting (pull-before-grant, the reference's
    # "args ready → lease dispatchable" contract).
    # ------------------------------------------------------------------
    def _args_ready(self, spec: LeaseRequest) -> bool:
        """True if every TOP-LEVEL arg is local, inline-fetchable, or
        errored (the worker can resolve all of them without blocking).
        Nested refs never gate dispatch — a task may be the very thing that
        unblocks the object a nested ref names."""
        for oid in spec.deps:
            if not self.store.contains(oid) and oid not in self._dep_ready_ids:
                return False
        return True

    def _park_for_deps(self, spec: LeaseRequest) -> None:
        missing = [
            oid
            for oid in spec.deps
            if not self.store.contains(oid) and oid not in self._dep_ready_ids
        ]
        with self._dep_cv:
            self._dep_waiting[spec.task_id] = (spec, set(missing))
            self._dep_cv.notify()

    def _dep_loop(self) -> None:
        """Resolve waiting leases: one batched head query per tick covers
        every missing arg; sealed-remote args trigger background pulls."""
        while not self._shutdown:
            if len(self._dep_ready_ids) > (1 << 16):
                self._dep_ready_ids.clear()  # cache, not ground truth
            with self._dep_cv:
                if not self._dep_waiting:
                    self._dep_cv.wait(timeout=0.5)
                    continue
                missing_all = sorted(
                    {o for _, m in self._dep_waiting.values() for o in m}
                )
            statuses: Dict[str, str] = {}
            unseen = [o for o in missing_all if not self.store.contains(o)]
            for o in missing_all:
                if o not in unseen:
                    statuses[o] = "local"
            if unseen:
                try:
                    replies = self.head.call(
                        "WaitObjectBatch",
                        {"object_ids": unseen, "timeout": 0.25},
                        timeout=15.0,
                    )
                except RpcError:
                    time.sleep(0.2)
                    continue
                for oid, rep in zip(unseen, replies):
                    st = rep["status"]
                    statuses[oid] = st
                    if st in ("inline", "error"):
                        # fetchable from the head without blocking
                        self._dep_ready_ids.add(oid)
                    elif st == "located":
                        self._prefetch(oid, rep["locations"])
            ready: List[LeaseRequest] = []
            with self._dep_cv:
                for tid in list(self._dep_waiting):
                    spec, missing = self._dep_waiting[tid]
                    missing.difference_update(
                        o
                        for o in list(missing)
                        if statuses.get(o) in ("local", "inline", "error")
                        or self.store.contains(o)
                        or o in self._dep_ready_ids
                    )
                    if not missing:
                        del self._dep_waiting[tid]
                        ready.append(spec)
            for spec in ready:
                self._admit_ready(spec)

    def _prefetch(self, oid: str, locations) -> None:
        """Background pull of a sealed remote object into the local store
        (pull_manager.h:40 analog), deduped while in flight."""
        with self._lock:
            if oid in self._pulls_in_flight:
                return
            self._pulls_in_flight.add(oid)

        def pull() -> None:
            try:
                for nid, addr in locations:
                    if nid == self.node_id or self.store.contains(oid):
                        return
                    # socket plane first (striped, resumable, lands
                    # straight in the arena); chunked RPC on any miss
                    try:
                        size = self._fetch_peer_to_store(
                            nid, oid, "task_args"
                        )
                    except KeyError:
                        continue
                    if size is not None:
                        self._report_to_head(
                            {
                                "node_id": self.node_id,
                                "seals": [
                                    SealInfo(
                                        object_id=oid,
                                        node_id=self.node_id,
                                        size=size,
                                    )
                                ],
                            }
                        )
                        return
                    try:
                        data = fetch_chunked(
                            self._peer(nid, addr), oid, purpose="task_args"
                        )
                    except (RpcError, KeyError, TimeoutError, ChunkFetchError):
                        continue
                    try:
                        self.store.put_bytes(oid, data)
                        self._report_to_head(
                            {
                                "node_id": self.node_id,
                                "seals": [
                                    SealInfo(
                                        object_id=oid,
                                        node_id=self.node_id,
                                        size=len(data),
                                    )
                                ],
                            }
                        )
                    except Exception:  # noqa: BLE001 - arena full
                        self._dep_ready_ids.add(oid)  # worker pulls inline
                    return
            finally:
                with self._lock:
                    self._pulls_in_flight.discard(oid)
                with self._dep_cv:
                    self._dep_cv.notify()

        self._exec_pool.submit(pull)

    def _admit_ready(self, spec: LeaseRequest) -> None:
        """Args are ready: NOW allocate resources + chips and queue for a
        worker; allocation failure spills back to the head (the resources
        went to leases that ran while this one waited)."""
        req = ResourceRequest.from_map(self.vocab, spec.resources)
        if spec.pg_reservation is not None:
            if not self._bundle_allocate(spec.pg_reservation, spec.resources):
                self._spillback(spec, "pg bundle busy after dep wait")
                return
            scalar_alloc = ("pg", spec.pg_reservation, dict(spec.resources))
        elif self.ledger.try_allocate(req):
            scalar_alloc = ("ledger", req)
        else:
            self._spillback(spec, "resources busy after dep wait")
            return
        assign = self.accel.allocate(spec.resources)
        if assign is None:
            self._release(scalar_alloc)
            self._spillback(spec, "chips busy after dep wait")
            return
        if _has_env(spec.runtime_env):
            self._exec_pool.submit(
                self._dispatch_pip_task, spec, scalar_alloc + (assign,)
            )
            return
        with self._task_cv:
            self._task_buf.append((spec, scalar_alloc + (assign,)))
            self._task_cv.notify()

    def _spillback(self, spec: LeaseRequest, reason: str) -> None:
        # requeue=True: pure resource contention must NOT burn the task's
        # retry budget (the grant path's "reject" has the same semantics)
        self._report_to_head(
            {
                "node_id": self.node_id,
                "available": self.ledger.avail_map(),
                "failed": [
                    {
                        "task_id": spec.task_id,
                        "reason": reason,
                        "retryable": True,
                        "requeue": True,
                    }
                ],
            }
        )

    PUSH_BATCH = 8

    def _task_drain_loop(self) -> None:
        """Single drainer: pairs queued plain tasks with idle workers in
        batches (worker_pool dispatch loop analog, batched)."""
        while not self._shutdown:
            with self._task_cv:
                while not self._task_buf and not self._shutdown:
                    self._task_cv.wait(timeout=0.5)
                if self._shutdown:
                    return
            handle = self._pop_idle_worker()
            with self._idle_cv:
                spare_workers = len(self._idle)
            with self._task_cv:
                # spread across idle workers first (process parallelism for
                # CPU-bound tasks); batch multiple per worker only when
                # tasks outnumber workers — the regime where the per-push
                # RPC amortization matters
                buffered = len(self._task_buf)
                per_worker = -(-buffered // (spare_workers + 1))  # ceil
                n = min(buffered, max(1, per_worker), self.PUSH_BATCH)
                items = [self._task_buf.popleft() for _ in range(n)]
            if handle is None:
                for spec, alloc in items:
                    self._release(alloc)
                    self._report_to_head(
                        {
                            "node_id": self.node_id,
                            "failed": [
                                {
                                    "task_id": spec.task_id,
                                    "reason": "no worker available",
                                    "retryable": True,
                                }
                            ],
                        }
                    )
                continue
            if not items:
                self._return_worker(handle)
                continue
            self._exec_pool.submit(self._run_batch_on_worker, items, handle)

    def _run_batch_on_worker(self, items, handle: _WorkerHandle) -> None:
        reqs = [
            self._push_req(spec, self._alloc_env(alloc))
            for spec, alloc in items
        ]
        now = time.monotonic()
        for spec, _ in items:
            if spec.kind == "task":
                handle.running[spec.task_id] = now
        try:
            with handle.lock:
                replies = handle.client.call(
                    "PushTaskBatch", reqs, timeout=None
                )
        except RpcError:
            for spec, _ in items:
                handle.running.pop(spec.task_id, None)
            for _, alloc in items:
                self._release(alloc)
            if not self._shutdown:
                self._on_worker_death(handle, [s for s, _ in items])
            return
        except BaseException:  # noqa: BLE001 - remote exception shipped back
            # a handler-level failure must not strand the leases with their
            # resources held and the worker never returned to the pool
            logger.exception("PushTaskBatch failed; requeueing %d", len(items))
            for spec, alloc in items:
                handle.running.pop(spec.task_id, None)
                self._release(alloc)
                self._spillback(spec, "worker push failed")
            self._return_worker(handle)
            return
        try:
            for (spec, alloc), reply in zip(items, replies):
                handle.running.pop(spec.task_id, None)
                self._finish_worker_reply(
                    spec, handle, alloc, reply, return_worker=False
                )
        finally:
            self._return_worker(handle)

    def _drain_async_methods(self, actor_id: str) -> None:
        """Single-flight batch pusher for one async actor's methods."""
        while True:
            with self._lock:
                buf = self._async_buf.get(actor_id)
                if not buf:
                    self._async_draining.discard(actor_id)
                    return
                specs = []
                while buf and len(specs) < 64:
                    specs.append(buf.popleft())
                worker_id = self._actor_workers.get(actor_id)
                handle = self._workers.get(worker_id) if worker_id else None
            if handle is None:
                self._report_to_head(
                    {
                        "node_id": self.node_id,
                        "failed": [
                            {
                                "task_id": s.task_id,
                                "reason": "actor worker is gone",
                                "retryable": False,
                            }
                            for s in specs
                        ],
                    }
                )
                continue
            try:
                replies = handle.client.call(
                    "PushTaskBatch",
                    [self._push_req(s) for s in specs],
                    timeout=None,
                )
            except RpcError:
                # clear the single-flight flag or the restarted actor's
                # methods would buffer forever with no drainer
                with self._lock:
                    self._async_draining.discard(actor_id)
                if not self._shutdown:
                    self._on_worker_death(handle, specs)
                return
            except BaseException:  # noqa: BLE001 - shipped remote exception
                logger.exception("async PushTaskBatch failed; requeueing")
                for s in specs:
                    self._spillback(s, "worker push failed")
                continue
            for s, reply in zip(specs, replies):
                if reply.get("status") == "async_pending":
                    with self._lock:
                        early = self._early_task_done.pop(s.task_id, None)
                        if early is None:
                            self._async_pending[s.task_id] = (s, handle)
                    if early is not None:
                        self._finish_worker_reply(s, handle, None, early)
                else:
                    self._finish_worker_reply(
                        s, handle, None, reply, return_worker=False
                    )

    def _drain_actor_fifo(self, actor_id: str) -> None:
        while True:
            with self._lock:
                fifo = self._actor_fifo.get(actor_id)
                if not fifo:
                    self._actor_draining.discard(actor_id)
                    return
                spec = fifo.pop(0)
                worker_id = self._actor_workers.get(actor_id)
                handle = self._workers.get(worker_id) if worker_id else None
            if handle is None:
                self._report_to_head(
                    {
                        "node_id": self.node_id,
                        "failed": [
                            {
                                "task_id": spec.task_id,
                                "reason": "actor worker is gone",
                                "retryable": False,
                            }
                        ],
                    }
                )
                continue
            self._run_on_worker(spec, handle, None)

    def _dispatch_to_worker(self, spec: LeaseRequest, alloc) -> None:
        handle = self._pop_idle_worker()
        if handle is None:
            self._release(alloc)
            self._report_to_head(
                {
                    "node_id": self.node_id,
                    "failed": [
                        {
                            "task_id": spec.task_id,
                            "reason": "no worker available",
                            "retryable": True,
                        }
                    ],
                }
            )
            return
        if spec.kind == "actor_creation":
            with self._lock:
                handle.actor_id = spec.actor_id
                if spec.runtime_env:
                    # env persists for the actor's life: deny later reuse
                    handle.env_tainted = True
                self._actor_workers[spec.actor_id] = handle.worker_id
                # kept for head-restart re-registration (_node_info):
                # the head rebuilds ActorInfo/name bindings from this
                self._actor_meta[spec.actor_id] = dict(spec.actor_meta or {})
            # an actor pins its worker for life; backfill the pool 1:1 so
            # the free pool never shrinks below num_workers (the reference
            # starts dedicated worker processes per actor on demand,
            # worker_pool.cc StartWorkerProcess) — the previous total-count
            # cap starved the Nth actor creation once N-1 actors held all
            # the workers. Workers still warming (prestarted or a peer
            # creation's backfill) count as free: the hole they will fill
            # is already covered.
            with self._idle_cv:
                free = len(self._idle) + self._spawns_pending
            if free < self._num_workers:
                self._spawn_worker()
        self._run_on_worker(spec, handle, alloc)

    def _dispatch_pip_task(self, spec: LeaseRequest, alloc) -> None:
        """Route a lease carrying a pip runtime env to a worker bound to
        that env (building it first if needed). Mirrors the reference's
        agent-side env creation before worker startup
        (_private/runtime_env/agent/main.py shape)."""
        # dispatch guard ref taken BEFORE ensure: the GC sweep must never
        # delete the env between its build and its worker's spawn. The
        # slice/key prologue sits INSIDE the failure path too: a malformed
        # runtime_env (e.g. pip+uv merged from job-level + task-level
        # envs) must release the allocation and report, not die silently
        # in the exec pool.
        guard_key = None
        try:
            env = env_slice(spec.runtime_env)
            kind = next(iter(env))
            guard_key = self._pip_mgr.key_of(env)
            self._pip_mgr.acquire(guard_key)
            key, env_dir = self._pip_mgr.ensure(env)
        except Exception as exc:  # noqa: BLE001 - build failure is final
            if guard_key is not None:
                self._pip_mgr.release(guard_key)
            self._release(alloc)
            self._report_to_head(
                {
                    "node_id": self.node_id,
                    "failed": [
                        {
                            "task_id": spec.task_id,
                            "reason": f"runtime_env build failed: {exc}",
                            "retryable": False,
                        }
                    ],
                }
            )
            return
        try:
            handle = self._pop_pip_worker(key, env_dir, kind=kind)
        except Exception:  # noqa: BLE001 - spawn failure (fork pressure)
            logger.exception("pip env worker spawn failed")
            handle = None
        finally:
            # the worker (if obtained) holds its own env ref now
            self._pip_mgr.release(guard_key)
        if handle is None:
            self._release(alloc)
            self._report_to_head(
                {
                    "node_id": self.node_id,
                    "failed": [
                        {
                            "task_id": spec.task_id,
                            "reason": "pip env worker unavailable",
                            "retryable": True,
                        }
                    ],
                }
            )
            return
        if spec.kind == "actor_creation":
            with self._lock:
                handle.actor_id = spec.actor_id
                handle.env_tainted = True  # env-bound worker: never reuse
                self._actor_workers[spec.actor_id] = handle.worker_id
                self._actor_meta[spec.actor_id] = dict(spec.actor_meta or {})
        self._run_on_worker(spec, handle, alloc)

    def _pop_pip_worker(
        self, key: str, env_dir: str, kind: str = "pip", timeout: float = 120.0
    ) -> Optional[_WorkerHandle]:
        """Idle env-bound worker, or spawn one (jax import makes worker
        startup seconds-scale; the deadline covers it)."""
        deadline = time.monotonic() + timeout
        with self._idle_cv:
            lst = self._pip_idle.get(key)
            if lst:
                return self._workers[lst.pop()]
        # the worker's env ref lives exactly as long as its handle: taken
        # here, released once by _on_worker_death / the GC reaper (a
        # straggler that registers after our deadline keeps its ref until
        # the health loop or reaper collects it)
        self._pip_mgr.acquire(key)
        try:
            self._spawn_worker(pip_env=(key, env_dir, kind))
        except BaseException:
            self._pip_mgr.release(key)
            raise
        with self._idle_cv:
            while True:
                lst = self._pip_idle.get(key)
                if lst:
                    return self._workers[lst.pop()]
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._shutdown:
                    return None
                self._idle_cv.wait(timeout=min(remaining, 0.5))

    def _pip_gc_loop(self) -> None:
        """Reap env workers idle past the threshold, GC unreferenced env
        dirs (the reference's runtime-env GC on idle), and trim the PLAIN
        idle pool back to num_workers — prestart/backfill surplus from a
        churn burst must not hold extra worker processes forever."""
        from ray_tpu.config import cfg

        while not self._shutdown:
            time.sleep(min(10.0, max(1.0, cfg.runtime_env_idle_gc_s / 3)))
            now = time.monotonic()
            victims: List[_WorkerHandle] = []
            with self._idle_cv:
                for key, lst in list(self._pip_idle.items()):
                    keep = []
                    for wid in lst:
                        h = self._workers.get(wid)
                        if h is None:
                            continue
                        if now - h.idle_since > cfg.runtime_env_idle_gc_s:
                            victims.append(h)
                        else:
                            keep.append(wid)
                    if keep:
                        self._pip_idle[key] = keep
                    else:
                        self._pip_idle.pop(key, None)
                # plain-pool trim: stalest first (pops take from the end,
                # so the front of the list has been idle longest)
                excess = len(self._idle) - self._num_workers
                if excess > 0:
                    for wid in list(self._idle):
                        if excess <= 0:
                            break
                        h = self._workers.get(wid)
                        if (
                            h is not None
                            and now - h.idle_since
                            > cfg.runtime_env_idle_gc_s
                        ):
                            self._idle.remove(wid)
                            self._workers.pop(wid, None)
                            victims.append(h)
                            excess -= 1
            pip_victims = 0
            for h in victims:
                if h.pip_key is not None:
                    with self._idle_cv:
                        first = (
                            self._workers.pop(h.worker_id, None) is not None
                        )
                    if first:  # may race a concurrent death observation
                        self._pip_mgr.release(h.pip_key)
                    pip_victims += 1
                try:
                    h.proc.terminate()
                except OSError:
                    pass
                self._close_worker_client(h)
            if victims:
                # the reaped processes' borrow counts die with them
                self._report_to_head(
                    {
                        "node_id": self.node_id,
                        "holders_gone": [h.worker_id for h in victims],
                    }
                )
            if pip_victims:
                self._pip_mgr.gc()

    def _push_req(self, spec: LeaseRequest, accel_env=None) -> dict:
        return {
            "task_id": spec.task_id,
            "kind": spec.kind,
            "actor_id": spec.actor_id,
            "payload": spec.payload,
            "return_ids": spec.return_ids,
            "arg_ids": spec.arg_ids,
            "name": spec.name,
            "runtime_env": spec.runtime_env,
            "actor_meta": spec.actor_meta,
            "accel_env": accel_env,
            "trace": spec.trace,
            "fn_blob": spec.fn_blob,
            "fn_id": spec.fn_id,
            "fn_cache": spec.fn_cache,
            "streaming": spec.streaming,
            "client_id": spec.client_id,
            "retry_exceptions": (
                spec.retry_exceptions and spec.attempt < spec.max_retries
            ),
        }

    @staticmethod
    def _alloc_env(alloc):
        """TPU_VISIBLE_CHIPS / CUDA_VISIBLE_DEVICES for a granted lease."""
        if alloc is None:
            return None
        assign = None
        if alloc[0] == "ledger" and len(alloc) > 2:
            assign = alloc[2]
        elif alloc[0] == "pg" and len(alloc) > 3:
            assign = alloc[3]
        if not assign:
            return None
        return NodeAcceleratorState.env_for(assign) or None

    def _run_on_worker(
        self, spec: LeaseRequest, handle: _WorkerHandle, alloc, serialize: bool = True
    ) -> None:
        import contextlib

        # async-actor methods skip the per-worker lock: the worker's event
        # loop multiplexes them (serialize=False from _h_execute_lease)
        guard = handle.lock if serialize else contextlib.nullcontext()
        if spec.kind == "task":
            handle.running[spec.task_id] = time.monotonic()
        try:
            with guard:  # per-worker ordering (actor sequential exec)
                reply = handle.client.call(
                    "PushTask",
                    self._push_req(spec, self._alloc_env(alloc)),
                    timeout=None,
                )
        except RpcError:
            handle.running.pop(spec.task_id, None)
            self._release(alloc)
            if not self._shutdown:
                self._on_worker_death(handle, [spec])
            return
        except BaseException:  # noqa: BLE001 - remote exception shipped back
            logger.exception("PushTask failed for %s; requeueing", spec.name)
            handle.running.pop(spec.task_id, None)
            self._release(alloc)
            self._spillback(spec, "worker push failed")
            if spec.kind == "task":
                self._return_worker(handle)
            return
        handle.running.pop(spec.task_id, None)
        if reply.get("status") == "async_pending":
            # the worker accepted the method onto its event loop and will
            # deliver the outcome via TaskDone — free this thread now.
            # A fast coroutine's TaskDone can BEAT this reply back to the
            # agent (two independent RPC paths); it parks in
            # _early_task_done and is consumed here.
            with self._lock:
                early = self._early_task_done.pop(spec.task_id, None)
                if early is None:
                    self._async_pending[spec.task_id] = (spec, handle)
            if early is not None:
                self._finish_worker_reply(spec, handle, None, early)
            return
        self._finish_worker_reply(spec, handle, alloc, reply)

    def _h_task_done(self, req: dict) -> None:
        """Completion callback for async-actor methods (worker → agent)."""
        with self._lock:
            entry = self._async_pending.pop(req["task_id"], None)
            if entry is None:
                # outran the worker's own PushTask reply: stash for the
                # dispatch thread (see _run_on_worker). Worker-death entries
                # land here too and are dropped with the handle.
                self._early_task_done[req["task_id"]] = req["reply"]
                return
        spec, handle = entry
        self._finish_worker_reply(spec, handle, None, req["reply"])

    def _finish_worker_reply(
        self,
        spec: LeaseRequest,
        handle: _WorkerHandle,
        alloc,
        reply: dict,
        return_worker: bool = True,
    ) -> None:
        status = reply.get("status")
        if spec.kind == "actor_creation" and status == "ok":
            # a live actor holds its lease resources for its lifetime
            # (GcsActorScheduler lease semantics); released on death/kill.
            with self._lock:
                self._actor_allocs[spec.actor_id] = alloc
                if reply.get("async_actor"):
                    self._async_actors.add(spec.actor_id)
        else:
            self._release(alloc)
        report: Dict[str, Any] = {
            "node_id": self.node_id,
            "available": self.ledger.avail_map(),
            "finished": [spec.task_id],
        }
        if reply.get("borrows"):
            report["borrows"] = [
                {"holder": handle.worker_id, "object_ids": reply["borrows"]}
            ]
        if status == "retry":
            report.pop("finished")
            report["failed"] = [
                {
                    "task_id": spec.task_id,
                    "reason": reply.get("error_repr", "task raised"),
                    "retryable": True,
                }
            ]
        else:
            report["seals"] = reply.get("seals", [])
            self._note_seals(report["seals"])
            if spec.kind == "actor_creation" and status == "ok":
                report["actors_alive"] = [
                    {
                        "actor_id": spec.actor_id,
                        "node_id": self.node_id,
                        "address": self.address,
                    }
                ]
            elif spec.kind == "actor_creation":
                report["actors_dead"] = [
                    {
                        "actor_id": spec.actor_id,
                        "reason": reply.get("error_repr", "init failed"),
                    }
                ]
        if (
            return_worker
            and spec.kind != "actor_method"
            and spec.kind != "actor_creation"
        ):
            self._return_worker(handle)
        self._report_to_head(report)

    def _release(self, alloc) -> None:
        if alloc is None:
            return
        if alloc[0] == "ledger":
            self.ledger.release(alloc[1])
            if len(alloc) > 2:
                self.accel.release(alloc[2])
        else:
            self._bundle_release(alloc[1], alloc[2])
            if len(alloc) > 3:
                self.accel.release(alloc[3])

    # ------------------------------------------------------------------
    # placement-group bundles (PlacementGroupResourceManager analog,
    # raylet/placement_group_resource_manager.cc)
    # ------------------------------------------------------------------
    def _h_prepare_bundles(self, req: dict) -> dict:
        pg_id, bundles = req["pg_id"], req["bundles"]
        agg: Dict[str, float] = {}
        for b in bundles.values():
            for k, v in b.items():
                agg[k] = agg.get(k, 0.0) + float(v)
        r = ResourceRequest.from_map(self.vocab, agg)
        if not self.ledger.try_allocate(r):
            return {"ok": False}
        with self._lock:
            self._bundles[pg_id] = {
                "state": "prepared",
                "agg": agg,
                "bundles": {int(i): dict(b) for i, b in bundles.items()},
            }
        return {"ok": True}

    def _h_commit_bundles(self, req: dict) -> None:
        with self._lock:
            entry = self._bundles.get(req["pg_id"])
            if entry is not None:
                entry["state"] = "committed"

    def _h_rollback_bundles(self, req: dict) -> None:
        self._h_return_bundles(req)

    def _h_return_bundles(self, req: dict) -> None:
        with self._lock:
            entry = self._bundles.pop(req["pg_id"], None)
        if entry is not None:
            self.ledger.release(
                ResourceRequest.from_map(self.vocab, entry["agg"])
            )

    def _bundle_allocate(self, reservation, resources: Dict[str, float]) -> bool:
        pg_id, idx = reservation
        with self._lock:
            entry = self._bundles.get(pg_id)
            if entry is None:
                return False
            bundle = entry["bundles"].get(int(idx))
            if bundle is None:
                return False
            for k, v in resources.items():
                if bundle.get(k, 0.0) < v - _EPS:
                    return False
            for k, v in resources.items():
                bundle[k] = bundle.get(k, 0.0) - v
            return True

    def _bundle_release(self, reservation, resources: Dict[str, float]) -> None:
        pg_id, idx = reservation
        with self._lock:
            entry = self._bundles.get(pg_id)
            if entry is None:
                return
            bundle = entry["bundles"].get(int(idx))
            if bundle is None:
                return
            for k, v in resources.items():
                bundle[k] = bundle.get(k, 0.0) + v

    # ------------------------------------------------------------------
    # object plane
    # ------------------------------------------------------------------
    def _h_store_object(self, req: dict) -> None:
        self.store.put_bytes(req["object_id"], req["data"])

    def _h_fetch_object(self, req: dict) -> bytes:
        with self._push_adm(req.get("purpose", "task_args")):
            data = self.store.get_bytes(req["object_id"])
            OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "rpc"})
            return data

    def _h_fetch_object_batch(self, req: dict) -> List[bytes]:
        with self._push_adm(req.get("purpose", "task_args")):
            out = [self.store.get_bytes(oid) for oid in req["object_ids"]]
            OBJECT_TRANSFER_BYTES.inc(
                sum(len(d) for d in out), labels={"path": "rpc"}
            )
            return out

    def _h_fetch_object_meta(self, req: dict) -> dict:
        """Chunked-pull handshake: size without bytes (KeyError when the
        object left this node — the puller tries the next replica)."""
        return {"size": self.store.object_size(req["object_id"])}

    def _h_fetch_object_chunk(self, req: dict) -> bytes:
        """One window of an object (push_manager chunk analog). Each
        chunk passes admission separately so a multi-GB pull cannot park
        a transfer slot for its whole duration."""
        with self._push_adm(req.get("purpose", "task_args")):
            data = self.store.get_range(
                req["object_id"], int(req["offset"]), int(req["length"])
            )
            OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "rpc"})
            return data

    def _h_delete_objects(self, req: dict) -> None:
        logger.debug(
            "DeleteObjects: %d ids (%s...)",
            len(req["object_ids"]),
            ",".join(o[:8] for o in req["object_ids"][:4]),
        )
        for oid in req["object_ids"]:
            try:
                self.store.delete(oid)
            except Exception:  # noqa: BLE001
                pass

    def _h_worker_put(self, req: dict) -> None:
        """Worker fallback put when the shm arena is unavailable/full."""
        self.store.put_bytes(req["object_id"], req["data"])

    def _h_ref_update(self, req: dict) -> None:
        """Worker → head refcount relay (workers only talk to their agent;
        the head is the refcount authority)."""
        self.head.call("RefUpdate", req, timeout=10.0)

    def _note_seals(self, seals) -> None:
        """Workers seal big objects straight into the shared arena;
        register them in the spill LRU book."""
        for s in seals:
            if (
                not s.is_error
                and s.inline_value is None
                and s.node_id == self.node_id
            ):
                self.store.note_external(s.object_id, s.size)

    def _h_worker_sealed(self, req: dict) -> None:
        """Out-of-band seal from a worker (ray_tpu.put inside a task,
        async-actor results, streaming-generator items). Worker registry
        deltas piggyback here (the seal channel IS the worker's metrics
        uplink): they queue pre-labeled and ride the agent's next
        metrics ship instead of triggering a head report of their own."""
        if req.get("metrics"):
            with self._metric_lock:
                self._worker_metric_relays.extend(req["metrics"])
        if not (
            req["seals"] or req.get("stream") or req.get("stream_done")
        ):
            return  # metrics-only push
        self._note_seals(req["seals"])
        report = {"node_id": self.node_id, "seals": req["seals"]}
        for k in ("stream", "stream_done"):
            if req.get(k):
                report[k] = req[k]
        self._report_to_head(report)

    def _h_stream_consumed(self, req: dict) -> dict:
        """Worker backpressure poll, relayed to the head's watermark."""
        return self.head.call("StreamConsumed", req, timeout=10.0)

    def _h_get_object_for_worker(self, req: dict) -> dict:
        """Local miss → pull from a remote node (PullManager analog,
        object_manager/pull_manager.h:40): locate via head, fetch chunked
        from the peer agent, cache into the local store."""
        oid = req["object_id"]
        if self.store.contains(oid):
            return self._local_reply(oid)
        # timeout=None means wait as long as the dependency takes (task-arg
        # waits are unbounded in the reference's LeaseDependencyManager).
        timeout = req.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        while deadline is None or time.monotonic() < deadline:
            reply = self.head.call(
                "WaitObject",
                {"object_id": oid, "timeout": 2.0},
                timeout=15.0,
            )
            status = reply["status"]
            if status == "error":
                return {"status": "error", "error": reply["error"]}
            if status == "inline":
                return {"status": "inline", "data": reply["data"]}
            if status == "located":
                remaining = None
                if deadline is not None:
                    remaining = max(0.1, deadline - time.monotonic())
                out = self._pull_located(
                    oid,
                    reply["locations"],
                    remaining,
                    purpose=req.get("purpose", "task_args"),
                )
                if out is not None:
                    return out
        return {"status": "timeout"}

    def _pull_located(
        self,
        oid: str,
        locations,
        wait_s: Optional[float] = None,
        purpose: str = "task_args",
    ) -> Optional[dict]:
        """Admission-controlled peer pull: concurrent requests for the same
        object coalesce behind one leader fetch, and in-flight transfers
        are bounded class-aware (GET > WAIT > TASK_ARGS — an interactive
        get is never queued behind a storm of task-arg prefetches)."""
        with self._lock:
            ev = self._pull_waiters.get(oid)
            leader = ev is None
            if leader:
                ev = self._pull_waiters[oid] = threading.Event()
        if not leader:
            # followers honor the CALLER's deadline, not a fixed park
            ev.wait(timeout=120.0 if wait_s is None else min(wait_s, 120.0))
            if self.store.contains(oid):
                return self._local_reply(oid)
            return None  # leader failed; retry via the locate loop
        gone_nodes: List[str] = []
        try:
            with self._pull_adm(purpose):
                for nid, addr in locations:
                    if nid == self.node_id:
                        if self.store.contains(oid):
                            return self._local_reply(oid)
                        continue
                    deadline = (
                        None
                        if wait_s is None
                        else time.monotonic() + wait_s
                    )
                    # socket plane first: striped scatter-gather pull
                    # over the cached peer link, landing straight in the
                    # arena (zero per-transfer head RPCs)
                    try:
                        size = self._fetch_peer_to_store(
                            nid, oid, purpose, deadline
                        )
                    except KeyError:
                        gone_nodes.append(nid)
                        continue
                    if size is not None:
                        self._report_to_head(
                            {
                                "node_id": self.node_id,
                                "seals": [
                                    SealInfo(
                                        object_id=oid,
                                        node_id=self.node_id,
                                        size=size,
                                    )
                                ],
                            }
                        )
                        return self._local_reply(oid)
                    try:
                        # streamed, chunked, resumable pull: bounded
                        # in-flight windows; a dropped chunk re-requests
                        # alone instead of restarting the object. The
                        # relocate hook re-resolves the source between
                        # chunk retries, so a mid-transfer source death
                        # aborts to the locate loop instead of burning
                        # the whole retry budget against a dead peer.
                        data = fetch_chunked(
                            self._peer(nid, addr),
                            oid,
                            purpose=purpose,
                            deadline=deadline,
                            relocate=self._make_relocate(oid, nid, addr),
                        )
                    except KeyError:
                        # DEFINITE miss: the peer answered and does not
                        # hold the object (evicted, lost mid-spill, or a
                        # stale directory row). Report it so the head
                        # prunes the location — and reconstructs through
                        # lineage if that was the last copy. Transient
                        # failures below never trigger this: a timeout
                        # must not cost a re-execution.
                        gone_nodes.append(nid)
                        continue
                    except (RpcError, TimeoutError, ChunkFetchError):
                        # RpcError: transport blip; TimeoutError: its
                        # push admission saturated; ChunkFetchError: a
                        # chunk died past its retry budget — try the next
                        # copy, then the locate loop
                        continue
                    try:
                        self.store.put_bytes(oid, data)
                        # advertise the new copy (object directory update)
                        self._report_to_head(
                            {
                                "node_id": self.node_id,
                                "seals": [
                                    SealInfo(
                                        object_id=oid,
                                        node_id=self.node_id,
                                        size=len(data),
                                    )
                                ],
                            }
                        )
                        return self._local_reply(oid)
                    except Exception:  # noqa: BLE001 - arena full
                        return {"status": "inline", "data": data}
            return None
        finally:
            with self._lock:
                self._pull_waiters.pop(oid, None)
            ev.set()
            if gone_nodes:
                self._report_to_head(
                    {
                        "node_id": self.node_id,
                        "objects_missing": [
                            {"object_id": oid, "node_ids": gone_nodes}
                        ],
                    }
                )

    def _make_relocate(self, oid: str, nid: str, addr: str):
        """Relocate hook for :func:`fetch_chunked`: one head locate
        round-trip re-resolving where ``oid`` lives NOW. Returns the
        client for the current source (still listed), a replacement
        replica's client (the directory moved it), or None (gone
        everywhere — the pull aborts so the caller re-plans via its
        locate loop / lineage reconstruction)."""

        def _relocate():
            try:
                rep = self.head.call(
                    "WaitObject",
                    {"object_id": oid, "timeout": 0.2},
                    timeout=10.0,
                    epoch=self._head_epoch,
                )
            except Exception:  # noqa: BLE001 - head unreachable: no verdict
                return self._peer(nid, addr)  # keep retrying the source
            if rep.get("status") != "located":
                return None  # inline/error/pending: stop pulling bytes
            live = {n: a for n, a in rep["locations"]}
            if nid in live:
                return self._peer(nid, live[nid])
            for n2, a2 in rep["locations"]:
                if n2 != self.node_id:
                    return self._peer(n2, a2)
            return None

        return _relocate

    def _local_reply(self, oid: str) -> dict:
        """Workers read 'local' objects straight from the shm arena; a
        spilled object is restored into the arena first (restore path); if
        it can't fit back, or with the in-memory fallback store (no shared
        pages), ship the bytes inline."""
        if self.store_path and self.store.restore_to_arena(oid):
            return {"status": "local"}
        data = self.store.get_bytes(oid)
        OBJECT_TRANSFER_BYTES.inc(len(data), labels={"path": "inline"})
        return {"status": "inline", "data": data}

    def _node_info(self) -> NodeInfo:
        with self._lock:
            hosted = [
                {"actor_id": aid, **self._actor_meta.get(aid, {})}
                for aid in self._actor_workers
            ]
            held_leases = list(self._task_leases)
        lister = getattr(self.store, "list_objects", None)
        return NodeInfo(
            node_id=self.node_id,
            address=self.address,
            resources=dict(self.resources),
            labels=self.labels,
            hosted_actors=hosted,
            # store inventory: a restarted head re-seeds its object
            # directory from this, so pre-restart refs keep resolving
            stored_objects=list(lister()) if lister is not None else [],
            # a restarted head reconciles these against its lease table
            # and releases any it no longer tracks (pinned-worker leak
            # guard across unpersisted head restarts)
            held_task_leases=held_leases,
            # cross-node data plane: advertised so the head can grant
            # peer links to this node (endpoint + token in the grant)
            data_endpoint=(
                self._data_server.endpoint
                if self._data_server is not None
                else ""
            ),
            net_token=(
                self.net_token if self._data_server is not None else ""
            ),
        )

    def _peer(self, node_id: str, address: str) -> RpcClient:
        with self._lock:
            client = self._peer_clients.get(node_id)
            if client is None or client.address != address:
                client = RpcClient(address)
                self._peer_clients[node_id] = client
            return client

    # ------------------------------------------------------------------
    # cross-node data plane (transport.py): socket-first peer pulls over
    # head-granted connection leases, chunked RPC as the fallback for
    # every failure class, RAY_TPU_NATIVE_NET=0 as the kill switch
    # ------------------------------------------------------------------
    def _grant_peer_link(self, node_id: str):
        """One head round-trip per (src, dst) pair — the ONLY control-
        plane involvement in the socket path; every later transfer to
        this peer reuses the cached grant head-free."""
        from .transport import PeerLink

        try:
            rep = self.head.call(
                "GrantPeerLink",
                {"src_node": self.node_id, "dst_node": node_id},
                timeout=10.0,
                epoch=self._head_epoch,
            )
        except (RpcError, RpcStaleEpochError):
            return None
        if not rep.get("granted"):
            return None
        return PeerLink(
            rep["link_id"],
            node_id,
            rep["endpoint"],
            rep["token"],
            rep.get("epoch"),
            src_node=self.node_id,
        )

    def _fetch_peer_to_store(
        self,
        nid: str,
        oid: str,
        purpose: str,
        deadline: Optional[float] = None,
    ) -> Optional[int]:
        """Socket pull of one object straight into the local store
        (striped, resumable, arena scatter-landing). Returns the size,
        or None when the socket plane cannot serve this transfer (link
        denied, handshake rejected, transport death past the stripe
        retry budget) — the caller falls back to chunked RPC. KeyError
        propagates: the peer answered and does not hold the object."""
        from .transport import LinkRejectedError, StripeFetchError

        if not cfg.native_net or nid == self.node_id:
            return None
        link = self._links.get(nid)
        if link is None:
            return None
        from .transport import fetch_to_store

        try:
            return fetch_to_store(
                link, oid, self.store, purpose=purpose, deadline=deadline
            )
        except KeyError:
            raise
        except LinkRejectedError as exc:
            # epoch re-fence or token rotation (peer agent restarted):
            # the cached grant is dead — drop it; the next transfer
            # re-grants through the head and picks up fresh credentials
            logger.info("peer link to %s rejected (%s); dropping", nid, exc)
            self._links.drop(nid, link.link_id)
            return None
        except (StripeFetchError, ConnectionError, TimeoutError, OSError):
            return None

    def _h_revoke_peer_link(self, req: dict) -> dict:
        """Head revoked a link we hold (its destination node died)."""
        return {
            "dropped": self._links.drop(
                req.get("node_id", ""), req.get("link_id")
            )
        }

    def _h_chaos_drop_peer_conn(self, req=None) -> dict:
        """Chaos fault: sever every live data socket this node is
        SERVING mid-transfer. Pullers' in-flight stripes fail and must
        resume (only the lost stripes re-fetch) — the invariant the
        chaos tier asserts."""
        if self._data_server is None:
            return {"dropped": 0, "reason": "no data server"}
        return {"dropped": self._data_server.chaos_drop()}

    def _link_maintenance(self) -> None:
        """Renew-while-hot + idle reclamation (report-loop cadence):
        recently-used link ids piggyback on the coalesced seal report;
        links idle past the TTL close their pooled connections and
        return the lease to the head."""
        hot = self._links.hot_links(cfg.peer_link_ttl_s)
        if hot:
            self._report_to_head(
                {"node_id": self.node_id, "peer_links": hot}
            )
        for link in self._links.sweep_idle(cfg.peer_link_idle_ttl_s):
            try:
                self.head.call(
                    "ReturnPeerLink",
                    {"link_id": link.link_id},
                    timeout=5.0,
                    epoch=self._head_epoch,
                )
            except (RpcError, RpcStaleEpochError):
                pass  # expiry sweep reclaims it server-side

    # ------------------------------------------------------------------
    # reporting (RaySyncer RESOURCE_VIEW analog). Reports are coalesced
    # opportunistically: an idle reporter sends immediately (no added
    # latency); under load, everything queued while the previous RPC was in
    # flight merges into ONE message — the RaySyncer batching that keeps
    # the head from drowning in per-task RPCs.
    # ------------------------------------------------------------------
    def _report_to_head(self, report: Dict[str, Any]) -> None:
        with self._report_cv:
            self._report_queue.append(report)
            self._report_cv.notify()

    @staticmethod
    def _merge_reports(reports: List[Dict[str, Any]]) -> Dict[str, Any]:
        merged: Dict[str, Any] = {}
        for r in reports:
            for k, v in r.items():
                if isinstance(v, list):
                    merged.setdefault(k, []).extend(v)
                else:
                    merged[k] = v  # node_id fixed; "available" latest wins
        return merged

    def _reporter_loop(self) -> None:
        while True:
            with self._report_cv:
                while not self._report_queue and not self._shutdown:
                    self._report_cv.wait(timeout=0.5)
                if self._shutdown and not self._report_queue:
                    return
                batch = self._report_queue
                self._report_queue = []
            report = self._merge_reports(batch)
            try:
                # retry budget rides a head restart (seal/stream/finished
                # entries are at-least-once; dropping them stranded
                # consumers — a seal that never lands means a get() that
                # never resolves)
                self.head.call(
                    "ReportSeals",
                    report,
                    timeout=10.0,
                    retries=8,
                    retry_interval=0.25,
                    epoch=self._head_epoch,
                )
            except RpcStaleEpochError:
                if self._shutdown:
                    return
                # the head restarted under us: our stamp predates its
                # rebuilt tables. Re-register (adopting the new epoch and
                # re-advertising actors/inventory/leases), THEN redeliver
                # — the report lands fenced-fresh or not at all.
                logger.warning(
                    "head epoch advanced; re-registering before redelivery"
                )
                self._re_register()
                with self._report_cv:
                    self._report_queue.insert(0, report)
            except RpcNotLeaderError as exc:
                if self._shutdown:
                    return
                # the head we know is fenced/standby: walk the candidate
                # list (its hint first) to the current leader, register
                # there, then redeliver. The rejection is one fast RTT
                # (handler-level, no transport retries), so pace the
                # loop while nobody is leading yet — same cadence as
                # the unreachable path below.
                found = self._failover_head(exc.leader_hint)
                with self._report_cv:
                    self._report_queue.insert(0, report)
                if not found:
                    time.sleep(0.5)
            except RpcError:
                if self._shutdown:
                    return
                # still unreachable after the in-call budget: requeue at
                # the FRONT so merge order is preserved, and let the
                # report loop's orphan timeout decide when to give up
                logger.warning("head unreachable; requeueing report")
                with self._report_cv:
                    self._report_queue.insert(0, report)
                time.sleep(0.5)

    def _ship_metrics(self) -> None:
        """Metrics federation tick (report-loop cadence, interval-gated):
        sync the dark-plane accumulators into this process's registry,
        collect its typed deltas, and send them — plus any relayed
        worker deltas — to the head on the coalesced report channel."""
        now = time.monotonic()
        if now - self._metrics_last_ship < cfg.metrics_interval_s:
            return
        self._metrics_last_ship = now
        from ray_tpu.util.metrics import sync_gauge

        from .event_loop import publish_dark_plane

        publish_dark_plane()
        try:
            st = self.store.stats()
            sync_gauge(
                "arena_used_bytes",
                float(st.get("used", 0)),
                "Shm arena bytes in use on this node.",
            )
            sync_gauge(
                "arena_capacity_bytes",
                float(st.get("capacity", 0)),
                "Shm arena capacity on this node.",
            )
        except Exception:  # noqa: BLE001 - store stats are optional
            pass
        records = self._metric_exporter.collect()
        with self._metric_lock:
            relays = self._worker_metric_relays
            self._worker_metric_relays = []
        entries: List[Dict[str, Any]] = []
        if records:
            entries.append(
                {
                    "node": self.node_id,
                    "role": "agent",
                    "records": records,
                }
            )
        entries.extend(relays)
        if entries:
            self._report_to_head(
                {"node_id": self.node_id, "metrics": entries}
            )

    def _re_register(self) -> None:
        """Resync with a restarted head: RegisterNode is fence-exempt by
        design, re-attaches this node's actors/store inventory/held
        leases, and its reply carries the NEW cluster epoch."""
        try:
            reply = self.head.call(
                "RegisterNode", self._node_info(), timeout=10.0
            )
            self._head_epoch = reply.get("epoch")
        except RpcNotLeaderError as exc:
            # registered against a fenced/standby head: follow the
            # leadership hint / candidate walk, then register there
            if self._failover_head(exc.leader_hint):
                try:
                    reply = self.head.call(
                        "RegisterNode", self._node_info(), timeout=10.0
                    )
                    self._head_epoch = reply.get("epoch")
                except (RpcError, RpcNotLeaderError):
                    pass  # next report tick retries the walk
        except RpcError:
            pass  # next report tick (or its stale rejection) retries

    def _failover_head(self, hint: str = "") -> bool:
        """Walk the head-candidate list (rpc.resolve_leader) and swap
        this agent's head channel to the current leader. Returns True
        when the channel moved (or already points at the leader)."""
        from .rpc import resolve_leader

        addr = resolve_leader(self.head_address, hint)
        if addr is None:
            return False
        if addr == self.head_address:
            return True
        logger.warning(
            "head leadership moved %s -> %s; re-pointing",
            self.head_address,
            addr,
        )
        old = self.head
        self.head_address = addr
        self.head = RpcClient(addr)
        try:
            old.close()
        except Exception:  # noqa: BLE001
            pass
        return True

    # a spawned worker gets this long to come up and register before its
    # reservation is reclaimed and the process killed (cold spawns pay a
    # full interpreter + import; generous beats flapping)
    SPAWN_REGISTER_TIMEOUT_S = 120.0

    # an orphaned agent (its head gone for good, e.g. a crashed test
    # driver) must not linger holding ports/arena/spill space forever; a
    # restarting head recovers in seconds, so a long grace is safe
    @property
    def ORPHAN_TIMEOUT_S(self) -> float:  # noqa: N802 - historical name
        from ray_tpu.config import cfg

        return cfg.orphan_timeout_s

    def _report_loop(self) -> None:
        version = 0
        last_head_contact = time.monotonic()
        last_link_tick = time.monotonic()
        while not self._shutdown:
            time.sleep(REPORT_PERIOD_S)
            version += 1
            # peer-link upkeep at ~TTL/2 cadence (renewals piggyback on
            # the coalesced seal report; idle links return their lease)
            if (
                time.monotonic() - last_link_tick
                > cfg.peer_link_ttl_s / 2.0
            ):
                last_link_tick = time.monotonic()
                try:
                    self._link_maintenance()
                except Exception:  # noqa: BLE001 - upkeep must not kill beats
                    logger.exception("peer-link maintenance failed")
            # respawn workers that died outside a push (including ones that
            # crashed at startup before ever registering). A spawn that
            # never registers within the timeout counts as dead too — a
            # wedged startup (e.g. accelerator transport hang) would
            # otherwise hold its _spawns_pending reservation forever and
            # suppress backfill/prestart for the rest of the agent's life.
            if self._zygote is not None:
                self._zygote.drain_exits()
            with self._lock:
                now = time.monotonic()
                dead = [
                    h
                    for h in self._workers.values()
                    if h.proc.poll() is not None
                    or (
                        h.spawn_pending
                        and h.spawned_at
                        and now - h.spawned_at > self.SPAWN_REGISTER_TIMEOUT_S
                    )
                ]
            for h in dead:
                self._on_worker_death(h, [])
            if cfg.metrics_federation:
                try:
                    self._ship_metrics()
                except Exception:  # noqa: BLE001 - never skip a beat
                    logger.debug("metrics ship failed", exc_info=True)
            try:
                reply = self.head.call(
                    "NodeReport",
                    NodeReport(
                        node_id=self.node_id,
                        available=self.ledger.avail_map(),
                        version=version,
                    ),
                    timeout=5.0,
                    epoch=self._head_epoch,
                )
                last_head_contact = time.monotonic()
                self._draining = bool(reply.get("draining"))
                if not reply.get("alive", True):
                    # a transient heartbeat gap (or a head restart) got us
                    # declared dead/unknown — rejoin with our live actors.
                    logger.warning("head declared us dead; re-registering")
                    self._re_register()
            except RpcStaleEpochError:
                # fenced out by a rebuilt head: re-registration IS the
                # resync protocol (and refreshes the epoch stamp)
                last_head_contact = time.monotonic()  # the head is alive
                logger.warning("stale cluster epoch; re-registering")
                self._re_register()
            except RpcNotLeaderError as exc:
                # the head we report to fenced itself (a standby
                # promoted elsewhere): walk to the leader + re-register
                last_head_contact = time.monotonic()
                logger.warning("head is not the leader; failing over")
                if self._failover_head(exc.leader_hint):
                    self._re_register()
            except RpcError:
                if (
                    time.monotonic() - last_head_contact
                    > self.ORPHAN_TIMEOUT_S
                ):
                    logger.warning(
                        "head unreachable for %.0fs; agent exiting",
                        self.ORPHAN_TIMEOUT_S,
                    )
                    threading.Thread(target=self.shutdown, daemon=True).start()
                    return
                continue
            except Exception:  # noqa: BLE001
                # One bad reply (e.g. a head-side handler bug re-raised over
                # RPC) must never kill the heartbeat thread permanently —
                # that would get this node declared dead with no rejoin.
                logger.exception("node report failed; retrying next tick")
                continue

    # ------------------------------------------------------------------
    # actor + lifecycle control
    # ------------------------------------------------------------------
    def _drop_actor_state(self, actor_id: str) -> None:
        """Forget all per-actor state. Caller holds self._lock."""
        self._actor_workers.pop(actor_id, None)
        self._actor_meta.pop(actor_id, None)
        self._async_actors.discard(actor_id)
        self._async_buf.pop(actor_id, None)
        self._release(self._actor_allocs.pop(actor_id, None))

    # ------------------------------------------------------------------
    # memory-pressure monitor (src/ray/common/pressure_memory_monitor.h
    # analog): /proc/meminfo is the source of truth; the victim is the
    # newest-dispatched plain task's worker — killing the process trips
    # the normal worker-death path, which requeues its lease retryably.
    # ------------------------------------------------------------------
    @staticmethod
    def _memory_usage_fraction() -> Optional[float]:
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    parts = line.split()
                    if parts[0] in ("MemTotal:", "MemAvailable:"):
                        info[parts[0][:-1]] = int(parts[1])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", 0)
            if total <= 0:
                return None
            return 1.0 - avail / total
        except OSError:
            return None

    def _pick_oom_victim(self):
        """Newest-task-first victim policy (the reference protects older
        work); actor workers are exempt — killing one loses state."""
        victim = None
        newest = -1.0
        with self._lock:
            for handle in self._workers.values():
                if handle.actor_id is not None:
                    continue
                # dispatch threads mutate .running without our lock:
                # snapshot the values to dodge mid-iteration resizes
                started_vals = list(dict(handle.running).values())
                if not started_vals:
                    continue
                started = max(started_vals)
                if started > newest:
                    newest = started
                    victim = handle
        return victim

    def _memory_monitor_loop(self) -> None:
        from ray_tpu.config import cfg

        while not self._shutdown:
            time.sleep(cfg.memory_monitor_interval_s)
            try:
                self._memory_monitor_tick()
            except Exception:  # noqa: BLE001 - the monitor must survive
                logger.exception("memory monitor tick failed")

    def _memory_monitor_tick(self) -> None:
        from ray_tpu.config import cfg

        frac = self._memory_usage_fraction()
        if frac is None or frac < cfg.memory_usage_threshold:
            return
        victim = self._pick_oom_victim()
        if victim is None:
            logger.warning(
                "memory pressure %.0f%% but no plain task to kill",
                frac * 100,
            )
            return
        self.metrics_oom_kills += 1
        logger.warning(
            "memory pressure %.0f%% >= %.0f%%: OOM-killing worker %s "
            "(newest task first, %d in flight)",
            frac * 100,
            cfg.memory_usage_threshold * 100,
            victim.worker_id[:8],
            len(victim.running),
        )
        try:
            victim.proc.kill()
        except OSError:
            pass
        # the blocked PushTask RPC fails -> _on_worker_death requeues

    def _h_cancel_lease(self, req: dict) -> dict:
        """Drop a not-yet-running lease (task batch buffer or dependency
        wait); its resources release. Running tasks are not preempted
        (non-force reference semantics)."""
        lid = req["task_id"]
        with self._task_cv:
            for item in list(self._task_buf):
                spec, alloc = item
                if spec.task_id == lid:
                    self._task_buf.remove(item)
                    self._release(alloc)
                    return {"cancelled": True}
        # dep-waiting entries are guarded by _dep_cv everywhere else; the
        # wrong lock here would race _dep_loop's iteration
        with self._dep_cv:
            entry = self._dep_waiting.pop(lid, None)
        if entry is not None:
            return {"cancelled": True}
        if req.get("force"):
            # force: kill the worker running it (plain tasks only; the
            # worker-death path reports the failure and the head, having
            # sealed the cancel, drops it instead of retrying)
            with self._lock:
                victim = next(
                    (
                        hdl
                        for hdl in self._workers.values()
                        if hdl.actor_id is None and lid in hdl.running
                    ),
                    None,
                )
            if victim is not None:
                try:
                    victim.proc.kill()
                except OSError:
                    pass
                return {"cancelled": True}
        return {"cancelled": False}

    def _h_actor_worker_address(self, req: dict) -> dict:
        """Direct actor calls: resolve the worker process hosting an actor
        so a caller can push method batches to it without head round trips
        (the reference's direct actor task submission,
        core_worker/task_submission/actor_task_submitter.h)."""
        with self._lock:
            worker_id = self._actor_workers.get(req["actor_id"])
            handle = self._workers.get(worker_id) if worker_id else None
            if handle is None or handle.client is None:
                raise RuntimeError(
                    f"actor {req['actor_id']} has no live worker on this node"
                )
            return {
                "address": handle.client.address,
                "async_actor": req["actor_id"] in self._async_actors,
            }

    # ------------------------------------------------------------------
    # task leases (worker_lease grants): pin a worker to an owner so it
    # can stream same-shape tasks caller->worker with no head hop. The
    # reference's raylet does the same per-task worker lease
    # (local_lease_manager.h); here the lease is long-lived and
    # multiplexed, and the head schedules GRANTS, not tasks.
    # ------------------------------------------------------------------
    def _activate_task_lease(self, spec: LeaseRequest, alloc) -> None:
        """Resources are allocated; now pin an idle worker and report the
        lease (worker address + chip env) to the head, which relays it to
        the waiting owner."""
        handle = self._pop_idle_worker(timeout=10.0)
        if handle is None or self._shutdown:
            if handle is not None:
                self._return_worker(handle)
            self._release(alloc)
            self._report_to_head(
                {
                    "node_id": self.node_id,
                    "available": self.ledger.avail_map(),
                    "task_leases": [
                        {
                            "lease_id": spec.task_id,
                            "ok": False,
                            "reason": "no idle worker",
                        }
                    ],
                }
            )
            return
        with self._lock:
            handle.lease_id = spec.task_id
            self._task_leases[spec.task_id] = {
                "worker_id": handle.worker_id,
                "alloc": alloc,
                "owner": spec.client_id,
                "granted_at": time.monotonic(),
            }
            self._lease_stats["granted"] += 1
        # the lease pins its worker like an actor: backfill 1:1 so the
        # free pool never shrinks below num_workers (warming spawns count)
        with self._idle_cv:
            free = len(self._idle) + self._spawns_pending
        if free < self._num_workers and not self._shutdown:
            try:
                self._spawn_worker()
            except Exception:  # noqa: BLE001 - report loop backfills later
                logger.exception("lease backfill spawn failed")
        self._report_to_head(
            {
                "node_id": self.node_id,
                "available": self.ledger.avail_map(),
                "task_leases": [
                    {
                        "lease_id": spec.task_id,
                        "ok": True,
                        "node_id": self.node_id,
                        "worker_id": handle.worker_id,
                        "worker_address": handle.client.address,
                        "accel_env": self._alloc_env(alloc),
                    }
                ],
            }
        )

    def _h_return_worker_lease(self, req: dict) -> dict:
        """Release a task lease (owner returned it on queue drain / idle
        TTL, or the head revoked it): free the shape allocation, tell the
        worker to drain + drop lease state, and return it to the idle
        pool."""
        lease_id = req["lease_id"]
        with self._lock:
            entry = self._task_leases.pop(lease_id, None)
            handle = (
                self._workers.get(entry["worker_id"]) if entry else None
            )
            if entry is not None:
                self._lease_stats["returned"] += 1
        if entry is None:
            return {"ok": False}
        self._release(entry["alloc"])
        if handle is not None and handle.lease_id == lease_id:
            handle.lease_id = None
            if handle.client is not None:
                try:
                    handle.client.call(
                        "LeaseRelease", {"lease_id": lease_id}, timeout=10.0
                    )
                except RpcError:
                    pass  # dying worker: the death path respawns it
            self._return_worker(handle)
        return {"ok": True}

    def _forward_to_actor_worker(self, method: str, req: dict) -> Any:
        """Relay a compiled-DAG program RPC to the worker process pinned to
        the actor (the driver only knows the agent's address)."""
        with self._lock:
            worker_id = self._actor_workers.get(req["actor_id"])
            handle = self._workers.get(worker_id) if worker_id else None
        if handle is None or handle.client is None:
            raise RuntimeError(
                f"actor {req['actor_id']} has no live worker on this node"
            )
        return handle.client.call(method, req, timeout=60.0)

    def _h_kill_actor(self, req: dict) -> None:
        aid = req["actor_id"]
        with self._lock:
            worker_id = self._actor_workers.get(aid)
            handle = self._workers.get(worker_id) if worker_id else None
            self._drop_actor_state(aid)
            # clean actor exit → scrub + reuse the worker instead of a
            # kill/respawn cycle (worker_pool.cc idle-worker reuse).
            # Denied across runtime envs: pip/conda workers run a
            # different interpreter/sys.path, and a persisted plain env
            # marked the process (env_tainted) — both die instead.
            reusable = (
                handle is not None
                and cfg.actor_worker_reuse
                and not self._shutdown
                and handle.pip_key is None
                and not handle.env_tainted
                and handle.client is not None
                and handle.proc.poll() is None
            )
            if handle is not None and not reusable:
                self._workers.pop(worker_id, None)
        if handle is None:
            return
        if reusable:
            try:
                reply = handle.client.call(
                    "ScrubActor", {"actor_id": aid}, timeout=30.0
                )
            except RpcError:
                reply = None
            if reply is not None and reply.get("ok"):
                with self._idle_cv:
                    handle.actor_id = None
                    self.pool_stats["reused"] += 1
                self._return_worker(handle)
                return
            if reply is not None:
                logger.info(
                    "worker %s not reusable (%s); re-forking",
                    handle.worker_id[:8],
                    reply.get("reason", "scrub failed"),
                )
            with self._lock:
                # may race a concurrent death observation — pop decides
                if self._workers.pop(handle.worker_id, None) is None:
                    return
        try:
            handle.proc.kill()
        except OSError:
            pass
        self._close_worker_client(handle)
        if not self._shutdown:
            self._spawn_worker()

    def _h_serve_stats(self, req: dict) -> dict:
        with self._lock:
            self._serve_stats[int(req["pid"])] = {
                "deployment": req.get("deployment", ""),
                "stats": req.get("stats") or {},
                "ts": time.monotonic(),
            }
        return {"ok": True}

    def _serve_debug_block(self) -> dict:
        """Aggregate fresh replica reports (caller holds self._lock):
        per-replica engine stats plus the node-wide prefix-cache hit
        rate — the DebugState ``serve`` block."""
        now = time.monotonic()
        replicas = []
        hits = misses = 0
        for pid, entry in list(self._serve_stats.items()):
            if now - entry["ts"] > 30.0:
                del self._serve_stats[pid]
                continue
            stats = entry["stats"]
            pc = stats.get("prefix_cache") or {}
            hits += int(pc.get("hits") or 0)
            misses += int(pc.get("misses") or 0)
            replicas.append(
                {"pid": pid, "deployment": entry["deployment"], **stats}
            )
        total = hits + misses
        return {
            "replicas": replicas,
            "prefix_cache_hits": hits,
            "prefix_cache_misses": misses,
            "prefix_cache_hit_rate": (
                round(hits / total, 4) if total else None
            ),
        }

    def _h_debug_state(self, req=None) -> dict:
        """Operator/debugging introspection (node_manager DebugString
        analog, node_manager.cc HandleGetNodeStats)."""
        from .event_loop import hotpath_state

        hotpath = hotpath_state()
        with self._lock:
            hits = self.pool_stats["hits"]
            misses = self.pool_stats["misses"]
            total = hits + misses
            return {
                # execution-plane hot path (this agent process's view:
                # wire counters, ring fills of co-resident channels)
                "hotpath": hotpath,
                "task_buf": [s.task_id for s, _ in self._task_buf],
                "dep_waiting": {
                    t: sorted(m) for t, (s, m) in self._dep_waiting.items()
                },
                "async_pending": sorted(self._async_pending),
                "idle_workers": list(self._idle),
                "num_workers": len(self._workers),
                # warm-pool effectiveness, alongside idle_workers: hit
                # rate of the idle pool plus spawn/reuse/prestart counts
                "pool": {
                    **self.pool_stats,
                    "hit_rate": round(hits / total, 4) if total else None,
                    "prestart_inflight": self._prestart_inflight,
                    "zygote_alive": bool(
                        self._zygote is not None and not self._zygote.broken
                    ),
                    # process-wide spawn latency (shared across co-located
                    # agents in tests; authoritative on a real node)
                    "spawn_ms_fork": WORKER_SPAWN_MS.summary(
                        {"path": "fork"}
                    ),
                    "spawn_ms_spawn": WORKER_SPAWN_MS.summary(
                        {"path": "spawn"}
                    ),
                },
                # task-lease dispatch plane: active leases (who holds
                # which worker) + grant/return/loss lifecycle counts.
                # Per-task inflight lives owner-side by design (the whole
                # point is that the hot path never touches this agent).
                "dispatch": {
                    "task_leases": [
                        {
                            "lease_id": lid,
                            "worker_id": e["worker_id"],
                            "owner": e["owner"],
                            "age_s": round(
                                time.monotonic() - e["granted_at"], 1
                            ),
                        }
                        for lid, e in self._task_leases.items()
                    ],
                    **self._lease_stats,
                },
                "available": self.ledger.avail_map(),
                "store": self.store.stats(),
                # zero-copy data-plane health: arena fill, chunked pulls
                # in flight, and bytes moved per path (process-wide —
                # co-located agents in tests share the counters)
                "object_plane": self._object_plane_state(),
                # serving plane: co-located replica engine stats + the
                # node-wide prefix-cache hit rate
                "serve": self._serve_debug_block(),
                "oom_kills": self.metrics_oom_kills,
                # instrumented_io_context analog: every handler counted+timed
                "rpc_handlers": HANDLER_STATS.snapshot(),
            }

    @staticmethod
    def _fetch_gate_state() -> dict:
        from .transport import FETCH_GATE

        return FETCH_GATE.snapshot()

    @staticmethod
    def _device_plane_block() -> dict:
        from ray_tpu.cluster import device_plane

        return device_plane.debug_block()

    def _object_plane_state(self) -> dict:
        from ray_tpu.native.spill import SHM_EVICTIONS

        st = self.store.stats()
        cap = st.get("capacity") or 0
        return {
            "arena_fill_pct": (
                round(100.0 * st.get("used", 0) / cap, 2) if cap else None
            ),
            "chunked_pulls_inflight": int(CHUNKED_PULLS_INFLIGHT.value()),
            "transfer_bytes": {
                path: int(OBJECT_TRANSFER_BYTES.value({"path": path}))
                for path in (
                    "shm",
                    "shm_copy",
                    "inline",
                    "rpc",
                    "socket",
                    "device",
                )
            },
            "transfer_chunk_ms": TRANSFER_CHUNK_MS.summary(),
            "transfer_stripe_ms": TRANSFER_STRIPE_MS.summary(),
            "shm_evictions": int(SHM_EVICTIONS.value()),
            "spilled_objects": st.get("spilled_objects", 0),
            # deleted-with-outstanding-pins entries still holding arena
            # space; nonzero after every reader released (or died and had
            # its pin log replayed) is a leak — the chaos soak asserts 0
            "arena_zombies": self.store.zombie_count(),
            # device-direct data plane: seal/land counters + whether the
            # plane is active in THIS process (workers land device-side;
            # the agent itself only ever stages host frames)
            "device": self._device_plane_block(),
            # cross-node data plane: this node's stripe server + its
            # cached peer links and the grant/reuse/revoke lifecycle
            # (process-wide counters, like every metric here)
            "net": {
                "enabled": bool(cfg.native_net),
                "endpoint": (
                    self._data_server.endpoint
                    if self._data_server is not None
                    else None
                ),
                "server": (
                    dict(self._data_server.stats)
                    if self._data_server is not None
                    else None
                ),
                "links": self._links.snapshot(),
                "peer_conn": {
                    "granted": int(PEER_CONN_GRANTED.value()),
                    "revoked": int(PEER_CONN_REVOKED.value()),
                    "reused": int(PEER_CONN_REUSED.value()),
                },
                # cross-fetch in-flight byte gate (shuffle reduce-side
                # arena backpressure): waits > 0 means concurrent pulls
                # actually queued behind the budget
                "fetch_gate": self._fetch_gate_state(),
            },
        }

    def _h_chaos_kill_zygote(self, req=None) -> dict:
        """Chaos fault: SIGKILL this node's fork-server. The next fork
        attempt marks the client broken and `_zygote_for_fork` restarts
        it (bounded); past the restart budget the agent cold-spawns
        forever — either way worker spawns keep succeeding, which is the
        invariant the chaos soak asserts."""
        z = self._zygote
        if z is None:
            return {"killed": False, "reason": "no zygote (cold-spawn mode)"}
        try:
            pid = z.proc.pid
            z.proc.kill()
        except OSError as exc:
            return {"killed": False, "reason": repr(exc)}
        return {"killed": True, "pid": pid}

    def _h_shutdown(self, req=None) -> None:
        threading.Thread(target=self.shutdown, daemon=True).start()

    def shutdown(self) -> None:
        self._shutdown = True
        with self._idle_cv:
            self._idle_cv.notify_all()
        with self._report_cv:
            self._report_cv.notify_all()
        with self._task_cv:
            self._task_cv.notify_all()
        with self._dep_cv:
            self._dep_cv.notify_all()
        for handle in list(self._workers.values()):
            try:
                handle.proc.terminate()
            except OSError:
                pass
        if self._zygote is not None:
            self._zygote.close()
        self._exec_pool.shutdown(wait=False, cancel_futures=True)
        # data plane down before the store: a mid-teardown stripe serve
        # must not race the arena unlink (teardown exactly-once — both
        # closes are idempotent)
        if self._data_server is not None:
            self._data_server.close()
        self._links.close()
        try:
            self.store.close(unlink=True)
        except Exception:  # noqa: BLE001
            pass
        self._server.stop()


def main() -> None:  # pragma: no cover - exercised via subprocess in tests
    import argparse
    import json

    parser = argparse.ArgumentParser(description="ray_tpu node agent")
    parser.add_argument("--head", required=True)
    parser.add_argument("--resources", default='{"CPU": 4}')
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--num-workers", type=int, default=None)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--store-capacity", type=int, default=1 << 28)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    agent = NodeAgent(
        head_address=args.head,
        resources=json.loads(args.resources),
        labels=json.loads(args.labels),
        num_workers=args.num_workers,
        store_capacity=args.store_capacity,
        node_id=args.node_id,
    )
    print(f"ray_tpu agent {agent.node_id} listening on {agent.address}", flush=True)
    try:
        while not agent._shutdown:
            time.sleep(0.5)
    except KeyboardInterrupt:
        agent.shutdown()


if __name__ == "__main__":
    main()
