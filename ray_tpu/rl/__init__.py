"""Online-RL continuous-learning loop (ISSUE 20): rollout → train →
publish, with every trajectory stamped by its weights epoch and every
publish fenced by the two-phase (seal → commit) head WAL protocol."""
from .loop import (  # noqa: F401
    OnlineRLLoop,
    RLLoopConfig,
    RolloutWorker,
    elastic_rl_init,
    elastic_rl_step,
    make_prompt,
    model_config_from_dict,
    model_config_to_dict,
)
from .publish import LocalEpochLedger, WeightsPublisher  # noqa: F401
from .trajectory import (  # noqa: F401
    Trajectory,
    TrajectoryFeed,
    decode_block,
    encode_block,
)
