"""Weights publisher: the two-phase (seal → commit) epoch fence.

A publish makes new params visible to the rollout fleet under a
strictly increasing **weights epoch**, fenced exactly like gang
epochs. The protocol against the head (or the in-process ledger when
no cluster is running):

1. ``WeightsPublishSeal`` reserves ``committed + 1`` and WALs the seal
   phase (replicated to standbys before the reply returns).
2. The params land in the object plane under ``(model_id, epoch)`` —
   the shm/device-frame weights hub when one is reachable, a local
   version store otherwise. Data before fence: a reader that sees the
   committed epoch can always pull its params.
3. ``WeightsPublishCommit`` flips the sealed epoch to committed (its
   own WAL record).

A head killed between 1 and 3 leaves the successor showing the OLD
committed epoch with a dangling seal — readers never see a torn
publish, and the publisher's retry loop simply re-seals against the
promoted head (same port, PR 12) and commits. A commit whose epoch is
not the currently sealed one is rejected ``stale`` and the publisher
restarts the cycle; the fence can only ever move forward.

``between_phases`` is the chaos injection hook: the soak's
``head_kill_mid_publish`` fault arms it to hold the publisher inside
the seal→commit window while the orchestrator kills the leader.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.util.metrics import Counter, Histogram

WEIGHTS_PUBLISHES = Counter(
    "rl_weights_publishes_total",
    "Committed weights-epoch publishes.",
    label_names=("deployment",),
)
WEIGHTS_PUBLISH_RETRIES = Counter(
    "rl_weights_publish_retries_total",
    "Publish cycles restarted (stale commit or head failover mid-phase).",
    label_names=("deployment",),
)
WEIGHTS_PUBLISH_MS = Histogram(
    "rl_weights_publish_ms",
    "Seal->commit wall time for one weights publish (ms).",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000),
    label_names=("deployment",),
)


class LocalEpochLedger:
    """The head's weights-epoch state machine, in-process — identical
    replies, same seal/commit fencing, no RPC. Lets the loop (and the
    fast tests / bench) run headless while exercising the same
    two-phase protocol."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[str, dict] = {}

    def _row(self, dep: str) -> dict:
        return self._rows.setdefault(
            dep, {"committed": 0, "meta": {}, "sealed": None}
        )

    def call(self, method: str, req: dict, **_kw) -> dict:
        with self._lock:
            w = self._row(req["deployment"])
            if method == "WeightsPublishSeal":
                epoch = int(w["committed"]) + 1
                w["sealed"] = {
                    "epoch": epoch,
                    "meta": dict(req.get("meta") or {}),
                }
                return {"epoch": epoch, "committed": int(w["committed"])}
            if method == "WeightsPublishCommit":
                epoch = int(req["epoch"])
                sealed = w.get("sealed")
                if int(w["committed"]) >= epoch:
                    return {"committed": int(w["committed"]), "stale": False}
                if sealed is None or int(sealed["epoch"]) != epoch:
                    return {"committed": int(w["committed"]), "stale": True}
                w["committed"] = epoch
                w["meta"] = dict(sealed.get("meta", {}))
                w["sealed"] = None
                return {"committed": epoch, "stale": False}
            if method == "WeightsEpochGet":
                return {
                    "committed": int(w["committed"]),
                    "meta": dict(w.get("meta", {})),
                    "sealed": dict(w["sealed"]) if w.get("sealed") else None,
                }
            raise ValueError(f"unknown method {method!r}")

    def close(self) -> None:
        pass


class WeightsPublisher:
    """Publish params under the two-phase weights-epoch fence.

    ``head_address`` of None runs against a private
    :class:`LocalEpochLedger`. Params for each committed epoch are
    retained in a local version store (and pushed through the node's
    :class:`~ray_tpu.serve.model_store.WeightsHub` when one is
    reachable) so rollout workers — and the chaos oracle — can fetch
    the exact tree behind any epoch.
    """

    def __init__(
        self,
        deployment: str,
        head_address: Optional[str] = None,
        model_id: str = "policy",
        use_hub: bool = False,
    ):
        self.deployment = deployment
        self.model_id = model_id
        if head_address is None:
            self._client = LocalEpochLedger()
        else:
            from ray_tpu.cluster.rpc import RpcClient

            self._client = RpcClient(head_address)
        self._hub = None
        if use_hub:
            try:
                from ray_tpu.serve.model_store import hub_from_node

                self._hub = hub_from_node(deployment)
            except Exception:  # noqa: BLE001 - hub is an optimisation
                self._hub = None
        self._versions: Dict[int, Any] = {}
        self._versions_lock = threading.Lock()
        # chaos hook: runs between seal and commit (the kill window)
        self.between_phases: Optional[Callable[[int], None]] = None

    # -- protocol ------------------------------------------------------
    def publish(self, params: Any, max_attempts: int = 8) -> int:
        """Run one full seal→stash→commit cycle; returns the committed
        epoch. Retries the WHOLE cycle on a stale commit or an RPC
        failure (head died mid-phase and a standby promoted on the same
        port) — each retry re-seals, so exactly one epoch ever lands."""
        from ray_tpu.cluster.rpc import RpcError

        t0 = time.monotonic()
        last_err: Optional[Exception] = None
        for attempt in range(max_attempts):
            if attempt:
                WEIGHTS_PUBLISH_RETRIES.inc(
                    labels={"deployment": self.deployment}
                )
                time.sleep(min(0.2 * attempt, 1.0))
            try:
                sealed = self._client.call(
                    "WeightsPublishSeal",
                    {
                        "deployment": self.deployment,
                        "meta": {"model": self.model_id},
                    },
                    timeout=10.0,
                    retries=3,
                )
                epoch = int(sealed["epoch"])
                self._stash(epoch, params)
                if self.between_phases is not None:
                    self.between_phases(epoch)
                reply = self._client.call(
                    "WeightsPublishCommit",
                    {"deployment": self.deployment, "epoch": epoch},
                    timeout=10.0,
                    retries=3,
                )
            except RpcError as e:
                last_err = e
                continue
            if reply.get("stale"):
                last_err = RuntimeError(
                    f"stale commit for epoch {epoch} "
                    f"(committed={reply.get('committed')})"
                )
                continue
            WEIGHTS_PUBLISHES.inc(labels={"deployment": self.deployment})
            WEIGHTS_PUBLISH_MS.observe(
                (time.monotonic() - t0) * 1000.0,
                labels={"deployment": self.deployment},
            )
            return int(reply["committed"])
        raise RuntimeError(
            f"weights publish failed after {max_attempts} attempts"
        ) from last_err

    def _stash(self, epoch: int, params: Any) -> None:
        with self._versions_lock:
            self._versions[epoch] = params
        if self._hub is not None:
            # idempotent: an existing (model, epoch) entry means a prior
            # attempt of this same publish already sealed it
            self._hub.ensure(self.model_id, epoch, params)

    def params_for(self, epoch: int) -> Optional[Any]:
        with self._versions_lock:
            p = self._versions.get(int(epoch))
        if p is not None:
            return p
        if self._hub is not None:
            return self._hub.pull(self.model_id, int(epoch))
        return None

    def current_epoch(self) -> dict:
        return self._client.call(
            "WeightsEpochGet",
            {"deployment": self.deployment},
            timeout=10.0,
            retries=3,
        )

    def close(self) -> None:
        try:
            self._client.close()
        except Exception:  # noqa: BLE001
            pass
