"""Trajectory plane for the online-RL loop (ISSUE 20).

Rollout replicas emit epoch-stamped trajectories; the trainer pulls
fixed-size batches per step. Two properties carry the whole chaos
story:

- **Every trajectory is stamped with the weights epoch it was generated
  under.** The feed enforces the off-policy staleness window at batch
  formation: a trajectory whose epoch is older than ``committed - K``
  is dropped and counted (``dropped_stale``) — never silently trained
  on.
- **Batch formation is idempotent per trainer step.** ``take_for_step``
  caches the batch it formed for a step, so a gang reshape that replays
  the step (PR 14 replays collectives under a new epoch) — or N ranks
  each asking for "the step-7 batch" — all see byte-identical data and
  nothing is double-counted. That is what makes the killed run's loss
  curve provably identical to the unkilled reference.

Accounting is conservation-law shaped so the chaos invariant can assert
zero loss anywhere in the pipe::

    emitted == trained + dropped_stale + in_flight   (unaccounted == 0)

Duplicates (a resumed rollout re-emitting a trajectory it already
delivered — the token-exact ``resume_from`` path makes this benign) are
deduplicated by trajectory id and counted separately; they never enter
``emitted``.

Blocks (``encode_block``/``decode_block``) are dicts of flat numpy
arrays — the shape the shuffle/object plane ships zero-copy, and what
``TrajectoryFeed.emit`` takes when it runs as a remote actor.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


@dataclass
class Trajectory:
    """One rollout: prompt + generated tokens, stamped with provenance."""

    traj_id: str
    prompt: List[int]
    tokens: List[int]  # full sequence: prompt + generated
    weights_epoch: int
    rollout_id: str = ""
    seed: int = 0


def encode_block(trajs: List[Trajectory]) -> Dict[str, Any]:
    """Pack trajectories into flat arrays (padded token matrix + lengths
    + epoch stamps) — the zero-copy-friendly wire shape."""
    n = len(trajs)
    width = max((len(t.tokens) for t in trajs), default=1)
    toks = np.zeros((n, width), dtype=np.int32)
    lens = np.zeros((n,), dtype=np.int32)
    plens = np.zeros((n,), dtype=np.int32)
    epochs = np.zeros((n,), dtype=np.int64)
    seeds = np.zeros((n,), dtype=np.int64)
    for i, t in enumerate(trajs):
        toks[i, : len(t.tokens)] = t.tokens
        lens[i] = len(t.tokens)
        plens[i] = len(t.prompt)
        epochs[i] = t.weights_epoch
        seeds[i] = t.seed
    return {
        "tokens": toks,
        "lengths": lens,
        "prompt_lengths": plens,
        "epochs": epochs,
        "seeds": seeds,
        "traj_ids": [t.traj_id for t in trajs],
        "rollout_ids": [t.rollout_id for t in trajs],
    }


def decode_block(block: Dict[str, Any]) -> List[Trajectory]:
    out: List[Trajectory] = []
    toks = np.asarray(block["tokens"])
    lens = np.asarray(block["lengths"])
    plens = np.asarray(block["prompt_lengths"])
    epochs = np.asarray(block["epochs"])
    seeds = np.asarray(block["seeds"])
    for i, tid in enumerate(block["traj_ids"]):
        full = [int(x) for x in toks[i, : int(lens[i])]]
        out.append(
            Trajectory(
                traj_id=tid,
                prompt=full[: int(plens[i])],
                tokens=full,
                weights_epoch=int(epochs[i]),
                rollout_id=block["rollout_ids"][i],
                seed=int(seeds[i]),
            )
        )
    return out


@dataclass
class _Accounting:
    emitted: int = 0
    trained: int = 0
    dropped_stale: int = 0
    duplicates: int = 0

    def as_dict(self, in_flight: int) -> Dict[str, int]:
        return {
            "emitted": self.emitted,
            "trained": self.trained,
            "dropped_stale": self.dropped_stale,
            "in_flight": in_flight,
            "duplicates": self.duplicates,
            "unaccounted": self.emitted
            - self.trained
            - self.dropped_stale
            - in_flight,
        }


class TrajectoryFeed:
    """Buffer between rollout replicas and the trainer.

    Plain object locally; the same class runs as a ``ray_tpu`` actor in
    cluster mode (every method takes/returns plain dicts and ints).
    """

    def __init__(self, staleness_window: Optional[int] = None):
        if staleness_window is None:
            from ray_tpu.config import cfg

            staleness_window = int(cfg.rl_staleness_window)
        self.staleness_window = int(staleness_window)
        self._lock = threading.Lock()
        self._buf: List[Trajectory] = []
        self._seen: set = set()
        self._acct = _Accounting()
        # step -> formed batch (idempotent replay under gang reshape)
        self._step_cache: Dict[int, Dict[str, Any]] = {}
        # latest committed weights epoch the publisher told us about —
        # the staleness floor when the consumer doesn't pass one
        self._epoch = 0
        # consumer pacing override (None = consumer's own default):
        # lets a driver throttle the trainer while rollouts warm up or
        # sprint it once collection stops
        self._pace: Optional[float] = None
        # cooperative-stop latch + its per-step decision cache: every
        # rank asking "stop at step s?" gets the answer the FIRST asker
        # got, so a gang breaks out of its loop together (the same
        # idempotence contract as the step batches)
        self._stop = False
        self._stop_cache: Dict[int, bool] = {}

    # -- producer side -------------------------------------------------
    def emit(self, block: Dict[str, Any]) -> Dict[str, int]:
        """Ingest one encoded block; duplicate traj_ids (resumed rollout
        re-emits) are dropped and counted, not double-buffered."""
        trajs = decode_block(block)
        with self._lock:
            fresh = 0
            for t in trajs:
                if t.traj_id in self._seen:
                    self._acct.duplicates += 1
                    continue
                self._seen.add(t.traj_id)
                self._buf.append(t)
                self._acct.emitted += 1
                fresh += 1
            return {"accepted": fresh, "duplicates": len(trajs) - fresh}

    def note_epoch(self, epoch: int) -> int:
        """Record a committed weights epoch (monotonic); the default
        staleness floor for consumers that don't pass their own."""
        with self._lock:
            self._epoch = max(self._epoch, int(epoch))
            return self._epoch

    def latest_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def set_pace(self, seconds: Optional[float]) -> Optional[float]:
        """Override the consumer's per-step pacing (None restores the
        consumer's own default)."""
        with self._lock:
            self._pace = None if seconds is None else float(seconds)
            return self._pace

    def pace(self) -> Optional[float]:
        with self._lock:
            return self._pace

    def request_stop(self) -> bool:
        """Latch a cooperative stop: consumers that honour
        ``stop_for_step`` finish their current step and exit."""
        with self._lock:
            self._stop = True
            return self._stop

    def stop_for_step(self, step: int) -> bool:
        """Whether the consumer should stop after ``step`` — idempotent
        per step (first ask decides, replays see the same answer), so
        every rank of an elastic gang breaks at the same step even when
        ``request_stop`` races their reads."""
        with self._lock:
            s = int(step)
            if s not in self._stop_cache:
                self._stop_cache[s] = self._stop
            return self._stop_cache[s]

    # -- consumer side -------------------------------------------------
    def take_for_step(
        self,
        step: int,
        n: int,
        current_epoch: Optional[int] = None,
        staleness_window: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """The batch for trainer step ``step`` (idempotent: the first
        call forms it, replays return the cached block verbatim).

        Formation first purges everything older than
        ``current_epoch - K`` from the buffer (counted
        ``dropped_stale``), then takes up to ``n`` trajectories in
        emission order (counted ``trained`` — a formed batch is always
        eventually trained: the elastic trainer replays the step until
        it lands). Returns None when the buffer is empty — and caches
        the None too, so a replayed step that originally found an empty
        buffer stays empty on replay instead of silently training data
        the recorded run never saw.
        """
        k = (
            self.staleness_window
            if staleness_window is None
            else int(staleness_window)
        )
        with self._lock:
            if step in self._step_cache:
                return self._step_cache[step]
            cur = self._epoch if current_epoch is None else int(current_epoch)
            floor = cur - k
            keep: List[Trajectory] = []
            for t in self._buf:
                if t.weights_epoch < floor:
                    self._acct.dropped_stale += 1
                else:
                    keep.append(t)
            self._buf = keep
            if not self._buf:
                self._step_cache[step] = None
                return None
            batch, self._buf = self._buf[:n], self._buf[n:]
            self._acct.trained += len(batch)
            block = encode_block(batch)
            self._step_cache[step] = block
            return block

    # -- introspection -------------------------------------------------
    def accounting(self) -> Dict[str, int]:
        with self._lock:
            return self._acct.as_dict(len(self._buf))

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)
