"""Online-RL continuous-learning loop (ISSUE 20).

The closed cycle::

    rollout replicas ──trajectories──▶ TrajectoryFeed ──batches──▶ trainer
         ▲                                                            │
         └────── hot-swap (swap_params) ◀── two-phase publish ◀───────┘

Rollout replicas are :class:`~ray_tpu.llm.continuous.
ContinuousBatchingEngine` instances generating deterministically
(greedy or per-request seeded), so every trajectory is reproducible
from ``(params-epoch, prompt, seed)`` — that is what lets chaos tests
assert token-exact resume and lets the bench prove loss-curve
continuity by rerunning the reference. The trainer takes real causal-LM
gradient steps (``jax.value_and_grad(tfm.loss_fn)`` + SGD) on the SAME
model the rollouts run, so a published epoch genuinely changes rollout
behaviour.

:class:`OnlineRLLoop` is the in-process driver (fast tests, the
``rl_loop`` bench tier, 2-core CPU friendly). For the cluster soak the
module exports ``elastic_rl_init``/``elastic_rl_step`` — an
:class:`~ray_tpu.train.ElasticTrainer` loop body that pulls its batches
from a :class:`TrajectoryFeed` actor by step index; the feed's
idempotent per-step batches are what keep the loss curve identical
across gang reshapes.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.continuous import ContinuousBatchingEngine
from ray_tpu.llm.engine import GenerationConfig
from ray_tpu.models import transformer as tfm
from ray_tpu.rl.publish import WeightsPublisher
from ray_tpu.rl.trajectory import Trajectory, TrajectoryFeed, encode_block


def make_prompt(
    seed: int, step: int, worker: int, i: int, length: int, vocab: int
) -> List[int]:
    """Deterministic synthetic prompt — same (seed, step, worker, i)
    always yields the same tokens, so reruns and resumed rollouts are
    comparable token-for-token. Token 0 is reserved for padding."""
    base = seed * 9973 + step * 131 + worker * 31 + i * 17
    return [((base + j * 7) % (vocab - 1)) + 1 for j in range(length)]


class RolloutWorker:
    """One rollout replica: a continuous-batching engine plus the
    published weights epoch it currently serves. ``set_weights`` is the
    hot-swap edge — epoch-fenced drain via ``swap_params`` (PR 18), so
    no in-flight stream ever mixes weights epochs."""

    def __init__(
        self,
        model_cfg: tfm.ModelConfig,
        params: Any,
        rollout_id: str,
        *,
        max_batch: int = 2,
        page_size: int = 8,
        n_pages: int = 64,
    ):
        self.rollout_id = rollout_id
        self.model_cfg = model_cfg
        self.engine = ContinuousBatchingEngine(
            model_cfg,
            params,
            max_batch=max_batch,
            page_size=page_size,
            n_pages=n_pages,
            model_id="epoch-0",
        )
        self.weights_epoch = 0

    def set_weights(self, epoch: int, params: Any) -> int:
        """Hot-swap to a published epoch (idempotent; stale epochs are
        no-ops — a replica never moves backwards)."""
        if int(epoch) <= self.weights_epoch:
            return self.weights_epoch
        self.engine.swap_params(params, model_id=f"epoch-{int(epoch)}")
        self.weights_epoch = int(epoch)
        return self.weights_epoch

    def rollout(
        self,
        specs: List[Dict[str, Any]],
        max_new_tokens: int,
        temperature: float = 0.0,
    ) -> Dict[str, Any]:
        """Generate one trajectory per spec (``{"traj_id", "prompt",
        "seed"}``), all stamped with the CURRENT weights epoch, returned
        as an encoded block ready for ``TrajectoryFeed.emit``."""
        epoch = self.weights_epoch
        ids = []
        for s in specs:
            gen = GenerationConfig(
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                seed=int(s.get("seed", 0)),
            )
            ids.append(self.engine.submit(list(s["prompt"]), gen))
        while any(r not in self.engine.results for r in ids):
            self.engine.step()
        trajs = []
        for s, rid in zip(specs, ids):
            out = self.engine.results.pop(rid)
            prompt = list(s["prompt"])
            trajs.append(
                Trajectory(
                    traj_id=s["traj_id"],
                    prompt=prompt,
                    tokens=prompt + list(out),
                    weights_epoch=epoch,
                    rollout_id=self.rollout_id,
                    seed=int(s.get("seed", 0)),
                )
            )
        return encode_block(trajs)

    def probe_first_token(self) -> None:
        """One greedy token end-to-end — the 'first serving token on the
        new weights' the publish-latency metric measures."""
        self.engine.generate_ids(
            [[1, 2, 3]], GenerationConfig(max_new_tokens=1)
        )


@dataclass
class RLLoopConfig:
    n_rollout_workers: int = 2
    prompts_per_step: int = 2  # per worker
    prompt_len: int = 8
    max_new_tokens: int = 8
    batch_size: int = 4
    lr: float = 1e-2
    total_steps: int = 12
    seed: int = 0
    temperature: float = 0.0
    staleness_window: Optional[int] = None  # None -> cfg.rl_staleness_window
    publish_interval: Optional[int] = None  # None -> cfg.rl_publish_interval_steps


class OnlineRLLoop:
    """In-process rollout→train→publish driver.

    ``head_address`` of None fences epochs through a local ledger —
    same two-phase protocol, no cluster. Everything downstream of the
    seed is deterministic, so two loops built from identical inputs
    produce identical loss curves (the continuity oracle)."""

    def __init__(
        self,
        model_cfg: tfm.ModelConfig,
        init_params: Any,
        loop_cfg: RLLoopConfig,
        head_address: Optional[str] = None,
        deployment: str = "rl-policy",
        use_hub: bool = False,
    ):
        from ray_tpu.config import cfg

        self.model_cfg = model_cfg
        self.lc = loop_cfg
        self.staleness_window = (
            int(cfg.rl_staleness_window)
            if loop_cfg.staleness_window is None
            else int(loop_cfg.staleness_window)
        )
        self.publish_interval = (
            int(cfg.rl_publish_interval_steps)
            if loop_cfg.publish_interval is None
            else int(loop_cfg.publish_interval)
        )
        self.publisher = WeightsPublisher(
            deployment, head_address, use_hub=use_hub
        )
        self.feed = TrajectoryFeed(self.staleness_window)
        self.params = init_params
        self.epoch = 0
        self.workers = [
            RolloutWorker(
                model_cfg,
                init_params,
                f"r{i}",
                max_batch=max(2, loop_cfg.prompts_per_step),
            )
            for i in range(loop_cfg.n_rollout_workers)
        ]
        self._vg = jax.jit(
            jax.value_and_grad(
                lambda p, t: tfm.loss_fn(p, t, self.model_cfg)
            )
        )
        self.losses: List[float] = []
        self.publish_ms: List[float] = []
        self.publish_to_first_token_ms: List[float] = []
        self.samples_trained = 0

    # -- one cycle -----------------------------------------------------
    def _collect(self, step: int) -> None:
        vocab = self.model_cfg.vocab_size
        for wi, w in enumerate(self.workers):
            specs = [
                {
                    "traj_id": f"{w.rollout_id}:s{step}:p{i}",
                    "prompt": make_prompt(
                        self.lc.seed, step, wi, i, self.lc.prompt_len, vocab
                    ),
                    "seed": self.lc.seed * 1000 + step * 10 + i,
                }
                for i in range(self.lc.prompts_per_step)
            ]
            self.feed.emit(
                w.rollout(specs, self.lc.max_new_tokens, self.lc.temperature)
            )

    def _train_step(self, step: int) -> Optional[float]:
        block = self.feed.take_for_step(
            step, self.lc.batch_size, self.epoch, self.staleness_window
        )
        if block is None:
            return None
        tokens = jnp.asarray(block["tokens"])
        loss, grads = self._vg(self.params, tokens)
        lr = self.lc.lr
        self.params = jax.tree.map(
            lambda p, g: p - lr * g, self.params, grads
        )
        self.samples_trained += int(tokens.shape[0])
        return float(loss)

    def _publish(self) -> None:
        t0 = time.monotonic()
        self.epoch = self.publisher.publish(self.params)
        self.feed.note_epoch(self.epoch)
        self.publish_ms.append((time.monotonic() - t0) * 1000.0)
        for w in self.workers:
            w.set_weights(self.epoch, self.params)
        self.workers[0].probe_first_token()
        self.publish_to_first_token_ms.append(
            (time.monotonic() - t0) * 1000.0
        )

    def run(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        for step in range(self.lc.total_steps):
            self._collect(step)
            loss = self._train_step(step)
            if loss is not None:
                self.losses.append(loss)
            if (step + 1) % self.publish_interval == 0:
                self._publish()
        wall = max(time.monotonic() - t0, 1e-9)
        acct = self.feed.accounting()
        return {
            "steps": self.lc.total_steps,
            "losses": list(self.losses),
            "weights_epoch": self.epoch,
            "samples_trained": self.samples_trained,
            "samples_per_s": self.samples_trained / wall,
            "publish_ms": list(self.publish_ms),
            "publish_to_first_token_ms": list(
                self.publish_to_first_token_ms
            ),
            "accounting": acct,
            "stale_dropped_frac": (
                acct["dropped_stale"] / acct["emitted"]
                if acct["emitted"]
                else 0.0
            ),
            "wall_s": wall,
        }

    def close(self) -> None:
        self.publisher.close()


# ---------------------------------------------------------------------------
# ElasticTrainer loop body (cluster soak): batches come from a
# TrajectoryFeed actor keyed by step index — idempotent across gang
# reshapes, so the killed run's loss curve matches the reference.
# ---------------------------------------------------------------------------
def model_config_to_dict(cfg: tfm.ModelConfig) -> Dict[str, Any]:
    d = dict(cfg.__dict__)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    return d


def model_config_from_dict(d: Dict[str, Any]) -> tfm.ModelConfig:
    d = dict(d)
    d["dtype"] = jnp.dtype(d["dtype"])
    return tfm.ModelConfig(**d)


def elastic_rl_init(config: Dict[str, Any]) -> Dict[str, Any]:
    mc = model_config_from_dict(config["model"])
    params = tfm.init_params(mc, jax.random.PRNGKey(int(config["seed"])))
    return {"params": params}


def elastic_rl_step(state, step, gang, config):
    """One elastic RL trainer step: pull the (idempotent) step batch
    from the feed actor, take a real CE gradient step, and run one
    epoch-fenced collective so membership changes surface here exactly
    like any SPMD loop."""
    import ray_tpu

    mc = model_config_from_dict(config["model"])
    feed = ray_tpu.get_actor(config["feed_actor"])
    # pacing: the feed's live override wins (lets a soak driver throttle
    # the trainer through a fault schedule, then sprint the tail),
    # falling back to the static config knob
    pace = float(config.get("step_sleep", 0.0))
    try:
        live = ray_tpu.get(feed.pace.remote(), timeout=30.0)
        if live is not None:
            pace = float(live)
    except Exception:  # noqa: BLE001 - feed actor mid-restart
        pass
    if pace > 0:
        time.sleep(pace)
    block = ray_tpu.get(
        feed.take_for_step.remote(step, int(config["batch_size"]))
    )
    params = state["params"]
    leaf0 = jax.tree_util.tree_leaves(params)[0]
    params_finite = bool(jnp.isfinite(jnp.sum(leaf0)))
    tok_max = -1
    loss_val = float("nan")
    if block is not None:
        tokens = jnp.asarray(np.asarray(block["tokens"]))
        tok_max = int(jnp.max(tokens))
        loss, grads = jax.value_and_grad(
            lambda p, t: tfm.loss_fn(p, t, mc)
        )(params, tokens)
        lr = float(config["lr"])
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        loss_val = float(loss)
    # the collective every step: a rank killed mid-step is detected by
    # the gang's epoch fence, the survivors reshape, and this step
    # replays — pulling the SAME batch from the feed's step cache
    partials = {v: {"one": np.ones(1)} for v in gang.owned_shards()}
    gang.allreduce_shards(partials)
    # cooperative stop: the feed's per-step-idempotent flag means every
    # rank sees the same answer for the same step, so the whole gang
    # breaks out of its loop together (a diverging rank would wedge the
    # next collective and take a needless reshape)
    stop = False
    try:
        stop = bool(ray_tpu.get(feed.stop_for_step.remote(step), timeout=30.0))
    except Exception:  # noqa: BLE001 - feed actor mid-restart
        pass
    return (
        {"params": params},
        {
            "step": step,
            "loss": loss_val,
            "world": gang.world,
            "stop": stop,
            # provenance for the soak's loss-continuity oracle: which
            # trajectories this rank actually trained on (empty batch
            # == the feed had nothing for this step)
            "traj_ids": list(block["traj_ids"]) if block is not None else None,
            "params_finite": params_finite,
            "tok_max": tok_max,
        },
    )
