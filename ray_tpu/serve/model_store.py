"""Model-weights hub in the shared-memory arena (hot-swap plane).

Variant/LoRA weights a deployment multiplexes are published ONCE per
node into the shm arena (the PR 3 object plane) under a deterministic
object id derived from ``(deployment, model_id, version)`` — the arena
itself is the index, first writer wins, concurrent publishes of the
same version are benign no-ops (same idiom as the prefix cache).

A replica swapping onto a cold model pulls the pytree back through the
zero-copy wire format: with the device plane on, every ``jax.Array``
leaf was sealed as a device frame at publish time, so ``pull`` lands
them with one ``device_put`` each straight from the arena pages — no
intermediate host materialisation, no pickle of device memory. Host
mode falls back to read-only numpy views; ``jnp.asarray`` in the model
forward pays the single H2D hop lazily.

Swap observability lives here too: every hot-swap's wall-clock, drain
time, and the first-token-on-new-weights latency are exported so the
bench's zero-stream-errors swap row has numbers to gate on.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Any, Optional

from ray_tpu.cluster import serialization as wire
from ray_tpu.util.metrics import Counter, Histogram

WEIGHT_SWAPS = Counter(
    "serve_weight_swaps_total",
    "Completed replica weight hot-swaps.",
    label_names=("deployment", "model"),
)
WEIGHT_SWAP_FAILURES = Counter(
    "serve_weight_swap_failures_total",
    "Weight hot-swaps that failed (pull miss, bad version, error).",
    label_names=("deployment", "model"),
)
WEIGHT_SWAP_MS = Histogram(
    "serve_weight_swap_ms",
    "End-to-end hot-swap wall time: drain + pull + install (ms).",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
    label_names=("deployment", "model"),
)
WEIGHT_SWAP_DRAIN_MS = Histogram(
    "serve_weight_swap_drain_ms",
    "Time draining in-flight generation on the old weights-epoch (ms).",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
    label_names=("deployment", "model"),
)
FIRST_TOKEN_NEW_WEIGHTS_MS = Histogram(
    "serve_first_token_new_weights_ms",
    "Latency from swap completion to the first token generated on the "
    "new weights-epoch (ms).",
    boundaries=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000),
    label_names=("deployment", "model"),
)


def weights_oid(deployment: str, model_id: str, version: int) -> str:
    """Deterministic arena object id for one published weights pytree —
    any process on the node derives the same id, so there is no side
    table to reconcile."""
    return hashlib.sha256(
        b"wts\0"
        + deployment.encode()
        + b"\0"
        + model_id.encode()
        + b"\0"
        + str(int(version)).encode()
    ).hexdigest()[:32]


class WeightsHub:
    """Publish/pull weights pytrees through a ``NativeObjectStore``-like
    object (needs ``put_frames``/``get_view``/``contains``/``delete``).

    Best-effort by design: a failed publish or a pull miss just means
    the caller falls back to its closure-captured variant params (the
    cold-start path) — correctness never depends on the arena.
    """

    def __init__(self, store, deployment: str):
        self.store = store
        self.deployment = deployment
        self._lock = threading.Lock()
        self._mine: dict = {}  # oid -> size, for best-effort cleanup

    # -- publish -------------------------------------------------------
    def publish(self, model_id: str, version: int, params: Any) -> bool:
        """Seal ``params`` into the arena under its deterministic oid.
        jax.Array leaves go in as device frames when the device plane is
        on (zero-copy export on host-aliasing backends). Returns False
        when the entry already exists or the arena cannot take it."""
        oid = weights_oid(self.deployment, model_id, version)
        try:
            if self.store.contains(oid):
                return False
        except Exception:  # noqa: BLE001
            return False
        meta = {
            "deployment": self.deployment,
            "model": model_id,
            "version": int(version),
        }
        try:
            parts, total = wire.dumps_parts((meta, params))
        except Exception:  # noqa: BLE001 - unsealable leaf
            return False
        for attempt in (0, 1):
            try:
                self.store.put_frames(oid, parts)
                break
            except KeyError:
                return False  # concurrent publisher won the race
            except MemoryError:
                if attempt == 1:
                    return False
                with self._lock:
                    # arena pressure: drop our own older versions first
                    self._evict_locked()
            except Exception:  # noqa: BLE001 - store gone
                return False
        with self._lock:
            self._mine[oid] = total
        return True

    def _evict_locked(self) -> None:
        while self._mine:
            oid, _size = self._mine.popitem()
            try:
                self.store.delete(oid)
            except Exception:  # noqa: BLE001 - already gone
                pass

    # -- pull ----------------------------------------------------------
    def pull(self, model_id: str, version: int) -> Optional[Any]:
        """The published pytree for ``(model_id, version)``, or None on
        a miss. Device-frame leaves come back as ``jax.Array`` (one
        device_put each, straight from the arena page — request the
        device landing explicitly so the wire layer knows the frames
        should not bounce through host staging); host-sealed leaves are
        READ-ONLY numpy views that alias the arena until the returned
        tree is garbage collected."""
        oid = weights_oid(self.deployment, model_id, version)
        try:
            view = self.store.get_view(oid)
        except KeyError:
            return None
        except Exception:  # noqa: BLE001
            return None
        try:
            from ray_tpu.cluster import device_plane as _dp

            if _dp.device_plane_enabled():
                with _dp.landing("device"):
                    meta, params = wire.loads(view)
            else:
                meta, params = wire.loads(view)
        except Exception:  # noqa: BLE001 - corrupt entry
            return None
        if meta.get("model") != model_id or meta.get("version") != int(
            version
        ):
            return None
        return params

    def ensure(self, model_id: str, version: int, params: Any) -> bool:
        """Publish-or-already-present: the idempotent shape a retrying
        publisher needs (the online-RL two-phase publish re-runs its
        whole cycle after a head failover — a version its earlier
        attempt already sealed must read as success, not a race loss)."""
        if self.contains(model_id, version):
            return True
        if self.publish(model_id, version, params):
            return True
        return self.contains(model_id, version)

    def contains(self, model_id: str, version: int) -> bool:
        try:
            return self.store.contains(
                weights_oid(self.deployment, model_id, version)
            )
        except Exception:  # noqa: BLE001
            return False


def hub_from_node(deployment: str) -> Optional[WeightsHub]:
    """A :class:`WeightsHub` over this node's shm arena (the worker's
    open handle, or the process-local fallback arena the prefix cache
    also uses); None when no native store is reachable."""
    from ray_tpu.serve.prefix_cache import node_store

    store = node_store()
    if store is None:
        return None
    return WeightsHub(store, deployment)
