"""Admission control for the serving plane.

Synergy-motivated (arxiv 2110.06073) resource-sensitive admission
instead of blind FIFO queueing: overload is rejected BEFORE any work is
accepted, with a typed :class:`Overloaded` the ingress maps to a
retryable 503 — never a silent queue that converts overload into
timeout storms. Three gates compose, checked in order:

1. **Token bucket** — sustained accept rate (``serve_admission_qps``)
   with a burst allowance; 0 disables the rate gate.
2. **In-flight depth** — admitted-but-unfinished requests are bounded
   (``serve_admission_max_inflight``); past the bound new arrivals
   queue (gate 3) or shed.
3. **Per-tenant weighted fair queueing** — arrivals that cannot be
   admitted immediately park in per-tenant queues and are granted in
   weighted virtual-finish-time order (classic WFQ): a tenant with
   weight 2 drains twice as fast as weight 1 under contention, and no
   tenant can starve another by flooding. The waiting room itself is
   bounded (``serve_admission_wait_cap``); beyond it arrivals shed
   immediately with ``reason="queue_full"``.

Every shed increments ``serve_shed_total{reason}`` and the router maps
it to ``serve_requests_total{code="503"}``.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Optional

from ray_tpu.util.metrics import Counter, Gauge

SERVE_SHED = Counter(
    "serve_shed_total",
    "Requests shed by serving-plane admission control.",
    label_names=("reason",),
)
SERVE_QUEUE_DEPTH = Gauge(
    "serve_queue_depth",
    "Admitted-but-unfinished serving requests (router in-flight depth).",
)
SERVE_WAITING = Gauge(
    "serve_admission_waiting",
    "Arrivals parked in the admission waiting room (WFQ queues).",
)


class Overloaded(RuntimeError):
    """Typed backpressure: the serving plane refused the request BEFORE
    accepting any work. Carries a client hint for retry pacing."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(
            f"serving plane overloaded ({reason}); "
            f"retry after {retry_after_s:.2f}s"
        )
        self.reason = reason
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Standard token bucket; ``rate <= 0`` means unlimited."""

    def __init__(self, rate: float, burst: float, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        self._refill(self._clock())
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def next_available_s(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens could be available (retry hint)."""
        if self.rate <= 0:
            return 0.0
        self._refill(self._clock())
        deficit = n - self._tokens
        return max(0.0, deficit / self.rate)


class Ticket:
    """One admitted request's hold on the in-flight depth; ``done()``
    releases it (idempotent)."""

    __slots__ = ("_ctl", "_released", "tenant")

    def __init__(self, ctl: "AdmissionController", tenant: str):
        self._ctl = ctl
        self.tenant = tenant
        self._released = False

    def done(self) -> None:
        if not self._released:
            self._released = True
            self._ctl._release()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.done()


class _Waiter:
    __slots__ = ("tenant", "vft", "seq", "granted", "abandoned", "cost")

    def __init__(self, tenant: str, vft: float, seq: int, cost: int = 0):
        self.tenant = tenant
        self.vft = vft  # WFQ virtual finish time
        self.seq = seq
        self.granted = False
        self.abandoned = False
        # approximate prefill cost (prompt tokens): aggregated per
        # tenant into the scheduler-facing demand pressure export
        self.cost = int(cost)


class AdmissionController:
    def __init__(
        self,
        *,
        qps: float = 0.0,
        burst: float = 32.0,
        max_inflight: int = 256,
        wait_cap: int = 128,
        wait_timeout_s: float = 2.0,
        tenant_weights: Optional[Dict[str, float]] = None,
        clock=time.monotonic,
    ):
        self._bucket = TokenBucket(qps, burst, clock=clock)
        self.max_inflight = max(1, int(max_inflight))
        self.wait_cap = max(0, int(wait_cap))
        self.wait_timeout_s = float(wait_timeout_s)
        self._weights = dict(tenant_weights or {})
        self._cv = threading.Condition()
        self._inflight = 0
        self._queues: Dict[str, deque] = {}
        self._waiting = 0
        self._vtime = 0.0  # global WFQ virtual time
        self._granted_pending = 0  # granted waiters not yet woken/claimed
        self._tenant_vft: Dict[str, float] = {}
        self._seq = itertools.count()
        self.sheds = 0
        self.admitted = 0
        # fleet-shard state (router fleet): per-tenant admits since the
        # last reconcile drain, plus the head's last global-budget word
        # (is there cluster-wide headroom, and how soon does the next
        # reconcile re-split rates) — used to fix the retry hint when
        # the LOCAL bucket is dry but the GLOBAL budget is not
        self._usage: Dict[str, int] = {}
        self._global_headroom = False
        self._reconcile_window_s = 0.0

    def _weight(self, tenant: str) -> float:
        return max(1e-6, float(self._weights.get(tenant, 1.0)))

    # -- the one public gate -------------------------------------------
    def admit(
        self,
        tenant: str = "default",
        timeout_s: Optional[float] = None,
        cost: int = 0,
    ) -> Ticket:
        """Admit one request or raise :class:`Overloaded`. Blocks up to
        ``timeout_s`` in the WFQ waiting room when the fast path is
        contended; a granted admission returns a :class:`Ticket` whose
        ``done()`` releases the in-flight slot. ``cost`` is the
        request's approximate prefill cost in prompt tokens — it does
        not change WFQ ordering, only the per-tenant pressure export."""
        timeout_s = (
            self.wait_timeout_s if timeout_s is None else float(timeout_s)
        )
        with self._cv:
            # fast path: nobody parked ahead of us and both gates open
            # (granted-but-unclaimed waiters already own depth slots —
            # ignoring them here would breach max_inflight under the
            # exact contention this gate exists for)
            if (
                self._waiting == 0
                and self._inflight + self._granted_pending
                < self.max_inflight
                and self._bucket.try_take()
            ):
                return self._grant_locked(tenant)
            if self._waiting >= self.wait_cap:
                return self._shed_locked("queue_full")
            waiter = self._park_locked(tenant, cost)
            deadline = time.monotonic() + timeout_s
            try:
                while True:
                    self._pump_locked()
                    if waiter.granted:
                        return self._grant_locked(tenant, pumped=True)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return self._shed_locked("timeout", waiter)
                    # wake early enough to re-check the refilling bucket
                    self._cv.wait(timeout=min(remaining, 0.05))
            except BaseException:
                self._abandon_locked(waiter)
                raise

    # -- internals (caller holds self._cv) -----------------------------
    def _grant_locked(self, tenant: str, pumped: bool = False) -> Ticket:
        if not pumped:
            # WFQ accounting for fast-path grants too, so virtual time
            # keeps moving and a later contended phase stays fair
            self._account_locked(tenant)
        else:
            # the pump reserved this slot when it granted the waiter
            self._granted_pending -= 1
        self._inflight += 1
        self.admitted += 1
        self._usage[tenant] = self._usage.get(tenant, 0) + 1
        SERVE_QUEUE_DEPTH.set(self._inflight)
        return Ticket(self, tenant)

    def _account_locked(self, tenant: str) -> float:
        start = max(self._vtime, self._tenant_vft.get(tenant, 0.0))
        vft = start + 1.0 / self._weight(tenant)
        self._tenant_vft[tenant] = vft
        return vft

    def _park_locked(self, tenant: str, cost: int = 0) -> _Waiter:
        waiter = _Waiter(
            tenant, self._account_locked(tenant), next(self._seq), cost
        )
        self._queues.setdefault(tenant, deque()).append(waiter)
        self._waiting += 1
        SERVE_WAITING.set(self._waiting)
        return waiter

    def _pump_locked(self) -> None:
        """Grant parked waiters in WFQ order while both gates are open.
        A granted-but-unclaimed waiter reserves depth via
        ``_granted_pending`` until its thread wakes and claims it."""
        while (
            self._waiting > 0
            and self._inflight + self._granted_pending < self.max_inflight
        ):
            head = None
            for q in self._queues.values():
                while q and q[0].abandoned:
                    q.popleft()
                if q and (
                    head is None
                    or (q[0].vft, q[0].seq) < (head.vft, head.seq)
                ):
                    head = q[0]
            if head is None:
                self._waiting = 0
                SERVE_WAITING.set(0)
                return
            if not self._bucket.try_take():
                return
            self._queues[head.tenant].popleft()
            self._waiting -= 1
            SERVE_WAITING.set(self._waiting)
            self._vtime = max(self._vtime, head.vft)
            head.granted = True
            self._granted_pending += 1
            self._cv.notify_all()

    def _shed_locked(self, reason: str, waiter: Optional[_Waiter] = None):
        if waiter is not None:
            if waiter.granted:
                # granted between our timeout check and now: take it
                self._granted_pending -= 1
                self._inflight += 1
                self.admitted += 1
                self._usage[waiter.tenant] = (
                    self._usage.get(waiter.tenant, 0) + 1
                )
                SERVE_QUEUE_DEPTH.set(self._inflight)
                return Ticket(self, waiter.tenant)
            self._abandon_locked(waiter)
        self.sheds += 1
        SERVE_SHED.inc(labels={"reason": reason})
        hint = self._bucket.next_available_s()
        if self._global_headroom and hint > self._reconcile_window_s > 0:
            # this shard's bucket is dry but the CLUSTER budget is not:
            # the next reconcile re-splits rates toward this router's
            # demand, so the honest backoff is one reconcile window —
            # not the local bucket's (misleadingly long) refill time
            hint = self._reconcile_window_s
        raise Overloaded(reason, retry_after_s=max(0.1, hint))

    def _abandon_locked(self, waiter: _Waiter) -> None:
        if waiter.abandoned:
            return
        if waiter.granted:
            # granted but never claimed (the waiting thread was
            # interrupted before waking): return the reserved depth slot
            # and hand it to the next waiter — leaving it would shrink
            # effective max_inflight by one forever
            waiter.abandoned = True
            self._granted_pending -= 1
            self._pump_locked()
            return
        waiter.abandoned = True
        self._waiting -= 1
        SERVE_WAITING.set(self._waiting)

    def _release(self) -> None:
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            SERVE_QUEUE_DEPTH.set(self._inflight)
            self._pump_locked()
            self._cv.notify_all()

    # -- fleet sharding (router fleet budget reconciliation) -----------
    def set_rate(self, rate: float, burst: Optional[float] = None) -> None:
        """Re-split: adopt this shard's share of the global admission
        rate. Accrued tokens are clamped to the new burst so a shrinking
        share cannot be spent from the old allowance."""
        with self._cv:
            bucket = self._bucket
            bucket._refill(bucket._clock())
            bucket.rate = float(rate)
            if burst is not None:
                bucket.burst = max(1.0, float(burst))
            bucket._tokens = min(bucket._tokens, bucket.burst)
            self._pump_locked()
            self._cv.notify_all()

    def note_global_budget(
        self, headroom: bool, reconcile_window_s: float
    ) -> None:
        """The head's last budget word: whether the CLUSTER-wide rate
        has headroom, and how long until the next re-split. Shapes the
        :class:`Overloaded` retry hint (see ``_shed_locked``)."""
        with self._cv:
            self._global_headroom = bool(headroom)
            self._reconcile_window_s = float(reconcile_window_s)

    def take_usage(self) -> Dict[str, int]:
        """Per-tenant admits since the last call (reconcile report);
        drains the counters."""
        with self._cv:
            usage, self._usage = self._usage, {}
            return usage

    def waiting_by_tenant(self) -> Dict[str, int]:
        """Parked demand per tenant (reconcile report)."""
        with self._cv:
            return {
                t: sum(1 for w in q if not w.abandoned)
                for t, q in self._queues.items()
                if q
            }

    def pressure_by_tenant(self) -> Dict[str, Dict[str, int]]:
        """Scheduler-facing serve pressure per tenant: parked request
        count AND their queued prefill tokens (the ``cost`` each admit
        carried). The fleet reconcile ships this to the head, which
        feeds it as demand rows to the multi-objective capacity
        kernel — capacity follows serve pressure, not just counts."""
        with self._cv:
            out: Dict[str, Dict[str, int]] = {}
            for t, q in self._queues.items():
                live = [w for w in q if not w.abandoned]
                if live:
                    out[t] = {
                        "waiting": len(live),
                        "waiting_tokens": sum(w.cost for w in live),
                    }
            return out

    def set_tenant_weights(self, weights: Dict[str, float]) -> None:
        with self._cv:
            self._weights = dict(weights or {})

    @property
    def tenant_weights(self) -> Dict[str, float]:
        with self._cv:
            return dict(self._weights)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        with self._cv:
            return {
                "inflight": self._inflight,
                "waiting": self._waiting,
                "admitted": self.admitted,
                "sheds": self.sheds,
                "max_inflight": self.max_inflight,
                "qps_limit": self._bucket.rate,
            }


def controller_from_cfg(
    tenant_weights: Optional[Dict[str, float]] = None,
) -> AdmissionController:
    from ray_tpu.config import cfg

    return AdmissionController(
        qps=float(cfg.serve_admission_qps),
        burst=float(cfg.serve_admission_burst),
        max_inflight=int(cfg.serve_admission_max_inflight),
        wait_cap=int(cfg.serve_admission_wait_cap),
        wait_timeout_s=float(cfg.serve_admission_timeout_s),
        tenant_weights=tenant_weights,
    )
