"""Cross-replica prefix/KV cache in the shared-memory arena.

Replica engines on the same node share prefilled KV pages through the
node's shm arena (the PR 3 object plane): an entry is the head-major KV
block ``[n_layers, n_kv_heads, pages, page, head_dim]`` for a
page-aligned token prefix, stored under a DETERMINISTIC object id
derived from a rolling page-chain hash — the arena itself is the index,
so there is no side table to keep consistent across replica processes
and no coordination on insert (first writer wins; a concurrent second
insert of the same prefix is a benign no-op).

Hits are **read-only view pins, not copies**: ``lookup`` resolves the
entry via ``NativeObjectStore.get_view`` and the zero-copy wire format,
so the returned numpy arrays alias the arena pages directly. The pin
follows PR 3/PR 5 semantics — a concurrent delete defers the arena free
to the last view's finalizer, and a SIGKILLed replica's outstanding
pins are replayed from its pin log by the agent (never leaked). The
engine copies the views into its device pool and drops them; the pin
dies with the views.

Capacity is self-policed per inserting process (``max_bytes``): the
oldest own entries are deleted first, and an arena-full put evicts then
retries once before giving up (caching is always best-effort — a miss
just recomputes prefill).
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.cluster import serialization as wire
from ray_tpu.util.metrics import Counter, Gauge

PREFIX_HITS = Counter(
    "serve_prefix_cache_hits_total",
    "Prefix-cache lookups that returned a pinned KV view.",
)
PREFIX_MISSES = Counter(
    "serve_prefix_cache_misses_total",
    "Prefix-cache lookups that found no cached prefix.",
)
PREFIX_INSERTS = Counter(
    "serve_prefix_cache_inserts_total",
    "Prefix KV blocks inserted into the shared arena.",
)
PREFIX_BYTES = Gauge(
    "serve_prefix_cache_bytes",
    "Bytes of prefix KV this process currently has inserted.",
)
PREFIX_HIT_TOKENS = Counter(
    "serve_prefix_cache_hit_tokens_total",
    "Prompt tokens whose prefill was skipped via cached KV.",
)


def _chain_hashes(tokens: Sequence[int], page: int) -> List[bytes]:
    """Rolling hash per FULL page: ``out[i]`` commits to tokens
    ``[0, (i+1)*page)``. A prefix of a prompt therefore shares the
    prompt's leading hashes — longest-prefix probing is just walking
    this list backwards."""
    out: List[bytes] = []
    h = hashlib.sha256()
    n_full = len(tokens) // page
    for i in range(n_full):
        chunk = tokens[i * page : (i + 1) * page]
        h.update(np.asarray(chunk, dtype=np.int64).tobytes())
        out.append(h.digest())
    return out


class PrefixHit:
    """One pinned cache hit: ``k``/``v`` cover ``tokens`` prompt tokens
    with shape ``[L, KH, pages, page, hd]``. For entries sealed as
    device frames (device-plane inserts) they are already ``jax.Array``
    — landed with ONE device_put straight from the arena page, no
    intermediate host copy; host-sealed entries come back as READ-ONLY
    numpy views over the arena. ``release()`` drops the views (and with
    them the arena pin) once the caller has copied/consumed them."""

    __slots__ = ("tokens", "k", "v", "_view")

    def __init__(self, tokens: int, k, v, view):
        self.tokens = tokens
        self.k = k
        self.v = v
        self._view = view

    def on_device(self) -> bool:
        """True when ``k``/``v`` landed as jax Arrays (device frames)."""
        try:
            import jax

            return isinstance(self.k, jax.Array)
        except ImportError:  # pragma: no cover
            return False

    def to_device(self):
        """``(k, v)`` device-resident: device-frame hits return their
        arrays as-is; host-view hits pay the one H2D hop here (after
        which the caller may ``release()`` — device_put copies)."""
        if self.on_device():
            return self.k, self.v
        import jax

        k, v = jax.device_put(self.k), jax.device_put(self.v)
        jax.block_until_ready((k, v))
        return k, v

    def release(self) -> None:
        self.k = self.v = self._view = None


class SharedPrefixCache:
    """Prefix-hash → KV-block cache over a ``NativeObjectStore``-like
    object (needs ``put_frames``/``get_view``/``contains``/``delete``/
    ``object_size``)."""

    def __init__(
        self,
        store,
        *,
        page_size: int,
        model_sig: str,
        max_bytes: int = 64 << 20,
        max_prefix_pages: int = 64,
    ):
        self.store = store
        self.page = int(page_size)
        self.model_sig = model_sig
        self._base_sig = model_sig
        self.max_bytes = int(max_bytes)
        self.max_prefix_pages = int(max_prefix_pages)
        self._lock = threading.Lock()
        # own inserts, insertion-ordered, oid -> size (self-policed budget)
        self._mine: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0

    def _oid(self, chain_hash: bytes) -> str:
        return hashlib.sha256(
            b"pfx\0" + self.model_sig.encode() + b"\0" + chain_hash
        ).hexdigest()[:32]

    def retag(self, tag: str) -> None:
        """Re-namespace the cache for a weights swap: KV computed under
        the previous weights must never be restored for the new ones, so
        the signature (and with it every object id) changes. Entries
        from the old epoch age out of the store via the shared budget;
        engines on other replicas that swap to the same ``tag`` land on
        the same namespace and keep sharing."""
        self.model_sig = f"{self._base_sig}|{tag}"

    # -- lookup --------------------------------------------------------
    def lookup(
        self, tokens: Sequence[int], max_tokens: Optional[int] = None
    ) -> Optional[PrefixHit]:
        """Longest cached page-aligned prefix of ``tokens`` (capped at
        ``max_tokens``), longest-first probe. Returns a pinned
        :class:`PrefixHit` or None."""
        hashes = _chain_hashes(tokens, self.page)
        if max_tokens is not None:
            hashes = hashes[: max(0, int(max_tokens)) // self.page]
        hashes = hashes[: self.max_prefix_pages]
        for i in range(len(hashes) - 1, -1, -1):
            oid = self._oid(hashes[i])
            try:
                view = self.store.get_view(oid)
            except KeyError:
                continue  # this prefix length not cached; try shorter
            except Exception:  # noqa: BLE001
                break  # store trouble: treat as a miss, don't spin
            try:
                meta, k, v = wire.loads(view)
            except Exception:  # noqa: BLE001 - corrupt entry: skip it
                continue
            if meta.get("tokens") != (i + 1) * self.page or meta.get(
                "page"
            ) != self.page:
                continue
            self.hits += 1
            PREFIX_HITS.inc()
            PREFIX_HIT_TOKENS.inc(meta["tokens"])
            return PrefixHit(meta["tokens"], k, v, view)
        self.misses += 1
        PREFIX_MISSES.inc()
        return None

    def contains_prefix(self, tokens: Sequence[int]) -> bool:
        """Cheap existence probe (hash + store.contains, no data read):
        callers use it to skip expensive KV extraction when the entry is
        already published."""
        n = (len(tokens) // self.page) * self.page
        if n == 0:
            return False
        chain = _chain_hashes(tokens[:n], self.page)
        try:
            return self.store.contains(self._oid(chain[-1]))
        except Exception:  # noqa: BLE001
            return True  # store trouble: claim present so callers skip

    # -- insert --------------------------------------------------------
    def insert(
        self,
        tokens: Sequence[int],
        k: np.ndarray,
        v: np.ndarray,
    ) -> bool:
        """Insert the KV block for the FULL pages of ``tokens``
        (``len(tokens)`` must be a page multiple matching ``k``'s page
        axis). Best-effort: returns False when the entry already exists
        or the arena cannot take it."""
        n = len(tokens)
        if n == 0 or n % self.page != 0:
            return False
        pages = n // self.page
        if pages > self.max_prefix_pages or k.shape[2] != pages:
            return False
        chain = _chain_hashes(tokens, self.page)
        oid = self._oid(chain[pages - 1])
        try:
            if self.store.contains(oid):
                return False
        except Exception:  # noqa: BLE001
            return False
        meta = {"tokens": n, "page": self.page}
        # numpy blocks need the contiguity fix-up here; jax blocks go in
        # as-is — the device-aware pickler seals them as device frames
        # (zero-copy export on host-aliasing backends) and the export
        # itself owns contiguity
        parts, total = wire.dumps_parts(
            (
                meta,
                np.ascontiguousarray(k) if isinstance(k, np.ndarray) else k,
                np.ascontiguousarray(v) if isinstance(v, np.ndarray) else v,
            )
        )
        with self._lock:
            self._evict_locked(self.max_bytes - total)
        for attempt in (0, 1):
            try:
                self.store.put_frames(oid, parts)
                break
            except KeyError:
                return False  # concurrent insert won the race
            except MemoryError:
                if attempt == 1:
                    return False
                with self._lock:
                    # arena pressure: give back half our budget and retry
                    self._evict_locked(self._bytes // 2)
            except Exception:  # noqa: BLE001 - store gone
                return False
        with self._lock:
            self._mine[oid] = total
            self._bytes += total
            PREFIX_BYTES.set(self._bytes)
        self.inserts += 1
        PREFIX_INSERTS.inc()
        return True

    def _evict_locked(self, budget: int) -> None:
        """Delete own oldest entries until our bytes fit ``budget``.
        Outstanding reader pins are safe: delete defers the arena free
        to the last view finalizer (zombie semantics)."""
        while self._mine and self._bytes > max(0, budget):
            oid, size = self._mine.popitem(last=False)
            self._bytes -= size
            try:
                self.store.delete(oid)
            except Exception:  # noqa: BLE001 - already evicted/spilled
                pass
        PREFIX_BYTES.set(self._bytes)

    # -- observability -------------------------------------------------
    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "inserts": self.inserts,
            "hit_rate": round(self.hits / total, 4) if total else None,
            "bytes": self._bytes,
        }


# ---------------------------------------------------------------------------
# store discovery: replicas bind to whatever arena their process can see
# ---------------------------------------------------------------------------
_local_store = None
_local_lock = threading.Lock()


def node_store():
    """The shm store shared by this process's node, if any.

    Inside a cluster worker this is the worker's already-open arena
    handle (pin tracking enabled, so SIGKILL replay covers cache pins).
    In a single-process runtime (tests, notebooks) a process-local
    arena is created on first use so co-resident replicas still share;
    returns None when the native store is unavailable.
    """
    from ray_tpu.cluster import worker as worker_mod

    w = getattr(worker_mod, "_CURRENT_WORKER", None)
    if w is not None and getattr(w, "store", None) is not None:
        return w.store
    global _local_store
    with _local_lock:
        if _local_store is None:
            try:
                import os
                import tempfile

                from ray_tpu.native import NativeObjectStore

                path = os.path.join(
                    tempfile.gettempdir(),
                    f"ray_tpu_prefix_{os.getpid()}.shm",
                )
                _local_store = NativeObjectStore(
                    path=path, capacity=128 << 20
                )
            except Exception:  # noqa: BLE001 - toolchain missing
                _local_store = False
    return _local_store or None


def cache_from_cfg(
    *, page_size: int, model_sig: str
) -> Optional[SharedPrefixCache]:
    """Build the node-shared cache per config; None when disabled or no
    arena is reachable."""
    from ray_tpu.config import cfg

    if not cfg.serve_prefix_cache:
        return None
    store = node_store()
    if store is None:
        return None
    return SharedPrefixCache(
        store,
        page_size=page_size,
        model_sig=model_sig,
        max_bytes=int(cfg.serve_prefix_cache_bytes),
    )
