"""ray_tpu.serve — model serving: deployments = replica actor fleets.

Analog of Ray Serve (/root/reference/python/ray/serve/): @deployment wraps a
class/function; serve.run() materializes replica actors behind a router that
picks replicas power-of-two-choices style (request_router/pow_2_router.py:27);
a controller loop autoscales replica counts toward
target_ongoing_requests (autoscaling_policy.py:296); an optional HTTP proxy
maps POST /<name> onto handles (proxy.py).
"""
from .admission import (  # noqa: F401
    AdmissionController,
    Overloaded,
)
from .deployment import (  # noqa: F401
    Application,
    Deployment,
    DeploymentHandle,
    NoReplicasForModel,
    deployment,
    get_deployment_handle,
    get_router,
    run,
    shutdown,
    start_grpc_ingress,
    start_http_proxy,
    start_proto_grpc_ingress,
)
from .fleet import (  # noqa: F401
    FleetStream,
    HashRing,
    RouterDeposedError,
    RouterFleet,
)
from .router import (  # noqa: F401
    RoutedStream,
    RouterKilled,
    ServeRouter,
    StreamRedirected,
)
from .slo_autoscaler import SLOAutoscaler, SLOConfig  # noqa: F401
