"""Protobuf-interop gRPC ingress for Serve deployments.

Capability analog of the reference's gRPC proxy serving USER-DEFINED
protobuf services (/root/reference/python/ray/serve/_private/proxy.py
gRPCProxy + grpc_util.py): users register their generated
``add_<Service>Servicer_to_server`` functions; the ingress implements
each servicer with a dynamic dispatcher that routes decoded request
messages to a deployment and returns its response messages — so any
grpcio client (Python, Go, ...) with its own compiled stubs calls
deployments directly, no ray_tpu on the client.

Routing: one registration binds one generated ``add_fn`` to one
deployment. A servicer method named ``Method`` dispatches to the
deployment's ``Method`` (or its snake_case form). Generated code picks
the handler TYPE from the .proto: unary methods return the replica's
response message; server-streaming methods route through
``num_returns="streaming"`` actor-method calls (streaming generators),
yielding each message as the replica seals it.
"""
from __future__ import annotations

import inspect
import re
from typing import Any, Callable, Dict, List, Tuple

import ray_tpu


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


class _DynamicServicer:
    """Stands in for the user's Servicer subclass: generated add_fns
    fetch method callables by attribute at registration time."""

    def __init__(self, route: Callable[[str, Any, Any], Any]):
        self._route = route

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        route = self._route

        def method(request, context):
            return route(method_name, request, context)

        method.__name__ = method_name
        return method


class ProtoGrpcIngress:
    """A plain grpcio server over the live deployment map."""

    CALL_TIMEOUT_S = 120.0

    def __init__(
        self,
        apps: Dict[str, Any],
        registrations: List[Tuple[Callable, str]],
        port: int = 0,
    ):
        import grpc
        from concurrent.futures import ThreadPoolExecutor

        self._apps = apps
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=16, thread_name_prefix="proto-grpc")
        )
        for add_fn, deployment in registrations:
            add_fn(_DynamicServicer(self._router(deployment)), self._server)
        self.port = self._server.add_insecure_port(f"0.0.0.0:{port}")
        if self.port == 0:
            raise RuntimeError(f"could not bind gRPC ingress port {port}")
        self._server.start()
        self.address = f"127.0.0.1:{self.port}"

    def _router(self, deployment: str) -> Callable:
        def route(method: str, request, context):
            import grpc

            rs = self._apps.get(deployment)
            if rs is None:
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"no such deployment: {deployment!r}",
                )
            user_cls = rs.dep.func_or_class
            target = getattr(user_cls, method, None)
            if target is None:
                target = getattr(user_cls, _snake(method), None)
            if target is None or not callable(target):
                context.abort(
                    grpc.StatusCode.UNIMPLEMENTED,
                    f"deployment {deployment!r} has no method "
                    f"{method!r} / {_snake(method)!r}",
                )
            name = target.__name__
            if inspect.isgeneratorfunction(target):
                # server-streaming: the replica yields response messages
                # through a streaming generator; each seals as its own
                # object and flows to the client as it lands
                gen = rs.submit_streaming(name, (request,), {})

                def iterate():
                    for ref in gen:
                        yield ray_tpu.get(ref, timeout=self.CALL_TIMEOUT_S)

                return iterate()
            ref = rs.submit(name, (request,), {})
            return ray_tpu.get(ref, timeout=self.CALL_TIMEOUT_S)

        return route

    def stop(self) -> None:
        self._server.stop(grace=1.0).wait()
