"""Deployments, router, autoscaling controller, HTTP proxy."""
from __future__ import annotations

import json
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    max_ongoing_requests: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    # serving-plane contract flags: resumable_streams declares that
    # ``stream_to`` regenerates deterministically and honors
    # ``resume_from`` (the router may fail a stream over mid-flight);
    # stats_method names a replica method the router's reporter may
    # call for engine-level stats (e.g. prefix-cache hit rate); slo
    # attaches an SLOConfig-driven autoscaler instead of the legacy
    # ongoing-count tick
    resumable_streams: bool = False
    stats_method: Optional[str] = None
    slo: Optional[Any] = None
    # per-tenant WFQ weights, enforced CLUSTER-WIDE by the router
    # fleet's budget reconciliation (a weight-3 tenant drains ~3x a
    # weight-1 tenant even when their streams land on different routers)
    tenant_weights: Optional[Dict[str, float]] = None
    # disaggregated serving (PR 18): the companion prefill deployment's
    # name — the router runs the prefill phase there and ships sealed KV
    # pages to this deployment's decode replicas; None = monolithic
    prefill_deployment: Optional[str] = None
    # model ids this deployment can multiplex (hot-swap targets); None
    # means single-model, any request "model" is accepted as-is
    models: Optional[List[str]] = None

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, **overrides) -> "Deployment":
        d = Deployment(
            self.func_or_class,
            overrides.pop("name", self.name),
            self.num_replicas,
            dict(self.ray_actor_options),
            self.max_ongoing_requests,
            self.autoscaling_config,
            self.resumable_streams,
            self.stats_method,
            self.slo,
            dict(self.tenant_weights) if self.tenant_weights else None,
            self.prefill_deployment,
            list(self.models) if self.models else None,
        )
        for k, v in overrides.items():
            setattr(d, k, v)
        return d


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(_func_or_class=None, *, name: Optional[str] = None, **opts):
    """@serve.deployment decorator (serve/api.py parity)."""

    def wrap(obj):
        dep_name = name or getattr(obj, "__name__", "deployment")
        if not isinstance(obj, type):
            fn = obj

            class _FuncDeployment:
                def __call__(self, *a, **kw):
                    return fn(*a, **kw)

            _FuncDeployment.__name__ = dep_name
            obj = _FuncDeployment
        d = Deployment(obj, dep_name)
        for k, v in opts.items():
            if k == "autoscaling_config" and isinstance(v, dict):
                v = AutoscalingConfig(**v)
            setattr(d, k, v)
        return d

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


class NoPreferredReplica(RuntimeError):
    """Raised by strict-preference dispatch when no candidate replica
    satisfies the caller's predicate (e.g. same-host for shm streaming)."""


class NoReplicasForModel(RuntimeError):
    """Retryable per-*model* empty set: the deployment has live replicas
    but none can serve the requested model id (unknown model, or every
    swap candidate is draining). Distinct from the all-replicas-dead
    RuntimeError so per-model SLO signals don't cross-contaminate."""

    def __init__(self, deployment: str, model: str, reason: str):
        super().__init__(
            f"no replicas for model {model!r} in deployment "
            f"{deployment!r} ({reason})"
        )
        self.deployment = deployment
        self.model = model


@dataclass
class _Replica:
    actor: Any
    ongoing: int = 0
    draining: bool = False
    # which weights this replica currently holds (model multiplexing):
    # None until the first model-tagged request lands on it
    model: Optional[str] = None


class _ReplicaSet:
    """Replica fleet + p2c router state for one deployment."""

    def __init__(self, app: Application):
        self.app = app
        self.dep = app.deployment
        self.lock = threading.Lock()
        self.replicas: List[_Replica] = []
        self.total_requests = 0
        self._outstanding: List[tuple] = []  # (ref, _Replica)
        self._watch_cv = threading.Condition(self.lock)
        self._watcher: Optional[threading.Thread] = None
        self._closed = False
        self._build_actor_class()
        n0 = (
            self.dep.autoscaling_config.min_replicas
            if self.dep.autoscaling_config
            else self.dep.num_replicas
        )
        # desired active-replica count: autoscaling moves it; replica
        # DEATH does not (the set backfills toward it)
        self.target = n0
        self.backfills = 0
        for _ in range(n0):
            self._add_replica()

    def _build_actor_class(self):
        cls = self.dep.func_or_class
        opts = dict(self.dep.ray_actor_options)
        opts.setdefault("max_concurrency", 8)
        init_args = []
        for a in self.app.init_args:
            if isinstance(a, Application):
                a = run(a)  # nested deployment → handle (model composition)
            init_args.append(a)
        self._actor_cls = ray_tpu.remote(**opts)(cls)
        self._init_args = tuple(init_args)

    def _add_replica(self):
        actor = self._actor_cls.remote(
            *self._init_args, **self.app.init_kwargs
        )
        with self.lock:
            self.replicas.append(_Replica(actor))

    def add_replica(self) -> None:
        """Scale up by one (autoscaler-facing): raises the desired count
        and creates the replica (the head scheduler places it)."""
        with self.lock:
            self.target += 1
        self._add_replica()

    def drain_one_replica(self) -> None:
        """Scale down by one with graceful drain (autoscaler-facing)."""
        with self.lock:
            self.target = max(1, self.target - 1)
        self._drain_one_replica()

    def note_replica_death(self, replica: "_Replica") -> None:
        """A replica's actor died (router dispatch/stream failure or the
        controller's liveness probe): drop it from routing immediately
        and backfill toward the desired count."""
        with self.lock:
            if replica not in self.replicas:
                return  # already reaped by a concurrent path
            self.replicas.remove(replica)
            need = (
                not replica.draining
                and not self._closed
                and len([r for r in self.replicas if not r.draining])
                < self.target
            )
        try:
            ray_tpu.kill(replica.actor)  # idempotent corpse cleanup
        except Exception:  # noqa: BLE001
            pass
        if need:
            self.backfills += 1
            self._add_replica()

    def reap_dead_replicas(self) -> int:
        """Controller-driven liveness sweep: probe each replica's actor
        state and reap the dead ones (detection without traffic, so a
        SIGKILLed idle replica still backfills). Control-plane cadence —
        never on the request path."""
        from ray_tpu.core.runtime import get_runtime

        try:
            rt = get_runtime()
        except Exception:  # noqa: BLE001
            return 0
        with self.lock:
            snapshot = list(self.replicas)
        reaped = 0
        for replica in snapshot:
            dead = False
            aid = getattr(replica.actor, "_actor_id", None)
            if aid is None:
                continue
            if getattr(rt, "is_remote", False):
                try:
                    info = rt._read(
                        "WaitActor", {"actor_id": aid, "timeout": 0.01}
                    )
                    dead = info.state == "DEAD"
                except Exception:  # noqa: BLE001 - head busy: skip sweep
                    continue
            else:
                state = rt._actors.get(aid)
                dead = state is not None and getattr(
                    state, "dead_forever", False
                )
            if dead:
                self.note_replica_death(replica)
                reaped += 1
        return reaped

    def _drain_one_replica(self):
        """Downscale with drain: stop routing to one idle replica and kill
        it; if none is idle, mark the emptiest as draining and kill it once
        its in-flight requests complete (the watcher does the final kill)."""
        with self.lock:
            active = [r for r in self.replicas if not r.draining]
            if len(active) <= 1:
                return
            idle = [r for r in active if r.ongoing == 0]
            victim = idle[0] if idle else min(active, key=lambda r: r.ongoing)
            victim.draining = True
            if victim.ongoing == 0:
                self.replicas.remove(victim)
                kill_now = True
            else:
                kill_now = False  # watcher kills at ongoing==0
        if kill_now:
            ray_tpu.kill(victim.actor)

    # power-of-two-choices routing (pow_2_router.py:27)
    def _pick_replica(self, prefer=None, strict_prefer=False,
                      model: Optional[str] = None) -> _Replica:
        # caller holds self.lock
        cands = [r for r in self.replicas if not r.draining]
        if not cands:
            cands = list(self.replicas)
        if not cands:
            # reachable since note_replica_death removes replicas: the
            # window between removing the last corpse and its backfill
            # registering must surface as a clear, retryable error
            raise RuntimeError(
                f"no live replicas for deployment {self.dep.name!r} "
                "(death backfill in progress)"
            )
        if prefer is not None:
            # affinity (e.g. same-host pinning for shm streaming):
            # restrict to preferred replicas when any exist. strict means
            # the caller's transport REQUIRES the predicate (a same-host-
            # only shm writer must never reach a cross-host replica) —
            # raise instead of falling through so the caller can switch
            # transports.
            preferred = [r for r in cands if prefer(r)]
            if preferred:
                cands = preferred
            elif strict_prefer:
                raise NoPreferredReplica(self.dep.name)
        if model is not None:
            # model multiplexing: p2c compares queue depth only WITHIN a
            # model's replica set — depths across different weights are
            # not comparable (a hot 70B variant's 3 ≠ a LoRA's 3)
            if self.dep.models is not None and model not in self.dep.models:
                raise NoReplicasForModel(
                    self.dep.name, model, "unknown model id"
                )
            same = [r for r in cands if r.model == model]
            if same:
                cands = same
            else:
                # cold model: swap on the least-loaded compatible
                # replica, preferring one that never took a variant.
                # Marked optimistically here (under self.lock) so a
                # concurrent second request for the same model routes to
                # this replica's queue instead of triggering a second
                # swap; the replica installs the weights on arrival.
                swappable = [r for r in cands if not r.draining]
                if not swappable:
                    raise NoReplicasForModel(
                        self.dep.name, model,
                        "all swap candidates draining",
                    )
                fresh = [r for r in swappable if r.model is None]
                victim = min(
                    fresh or swappable, key=lambda r: r.ongoing
                )
                victim.model = model
                return victim
        if len(cands) == 1:
            return cands[0]
        a, b = random.sample(cands, 2)
        return a if a.ongoing <= b.ongoing else b

    def submit(self, method: str, args, kwargs, prefer=None,
               strict_prefer=False):
        ref, _ = self.submit_traced(
            method, args, kwargs, prefer, strict_prefer
        )
        return ref

    def submit_traced(self, method: str, args, kwargs, prefer=None,
                      strict_prefer=False, model: Optional[str] = None):
        """Like ``submit`` but also returns the chosen replica — the
        serving router needs it for failover bookkeeping and
        lease-channel accounting."""
        with self.lock:
            replica = self._pick_replica(prefer, strict_prefer, model)
            replica.ongoing += 1
            self.total_requests += 1
            actor = replica.actor
        try:
            ref = getattr(actor, method).remote(*args, **kwargs)
        except BaseException:
            with self.lock:
                replica.ongoing -= 1
            raise
        with self._watch_cv:
            self._outstanding.append((ref, replica))
            if self._watcher is None or not self._watcher.is_alive():
                self._watcher = threading.Thread(
                    target=self._watch_loop,
                    name=f"serve-watch-{self.dep.name}",
                    daemon=True,
                )
                self._watcher.start()
            self._watch_cv.notify()
        return ref, replica

    class _StreamRequest:
        """Iterator over a streaming replica call that releases the
        replica's ongoing count exactly once — on exhaustion, error,
        close, OR drop-before-first-next (a generator's ``finally`` never
        runs if its frame never starts, which leaked the count when a
        gRPC client cancelled before the first message)."""

        def __init__(self, rs, replica, gen):
            self._rs = rs
            self._replica = replica
            self._gen = gen
            self._done = False

        def _finish(self) -> None:
            if not self._done:
                self._done = True
                self._rs._stream_finished(self._replica)

        def __iter__(self):
            return self

        def __next__(self):
            if self._done:
                raise StopIteration
            try:
                return next(self._gen)
            except BaseException:
                self._finish()
                raise

        def close(self) -> None:
            self._finish()

        def __del__(self):
            self._finish()

    def submit_streaming(self, method: str, args, kwargs):
        """Route a server-streaming call to a replica as a
        num_returns="streaming" actor method; returns an iterator of
        item ObjectRefs. The replica's ongoing count holds until the
        stream is fully consumed (or dropped), then drains like any
        completed request. Cluster runtime only (the in-process runtime
        has no per-item actor-method streaming)."""
        with self.lock:
            replica = self._pick_replica(None, False)
            replica.ongoing += 1
            self.total_requests += 1
            actor = replica.actor
        try:
            gen = (
                getattr(actor, method)
                .options(num_returns="streaming")
                .remote(*args, **kwargs)
            )
        except BaseException:
            with self.lock:
                replica.ongoing -= 1
            raise

        return self._StreamRequest(self, replica, gen)

    def _stream_finished(self, replica) -> None:
        """Release one finished request's hold on ``replica`` (shared by
        the completion watcher and streaming requests): decrement the
        ongoing count and finish a draining replica once idle."""
        to_kill = None
        with self.lock:
            replica.ongoing -= 1
            if (
                replica.draining
                and replica.ongoing == 0
                and replica in self.replicas
            ):
                self.replicas.remove(replica)
                to_kill = replica
        if to_kill is not None:
            ray_tpu.kill(to_kill.actor)

    def _watch_loop(self):
        """Single completion watcher: decrements in-flight counters when the
        request's result seals (never on a timeout), and finishes draining
        replicas."""
        while True:
            with self._watch_cv:
                while not self._outstanding and not self._closed:
                    self._watch_cv.wait(timeout=1.0)
                if self._closed:
                    return
                snapshot = list(self._outstanding)
            refs = [ref for ref, _ in snapshot]
            ready, _ = ray_tpu.wait(
                refs, num_returns=1, timeout=0.2
            )
            if not ready:
                continue
            ready_set = {r.hex for r in ready}
            finished = []
            with self._watch_cv:
                still = []
                for ref, replica in self._outstanding:
                    if ref.hex in ready_set:
                        finished.append(replica)
                    else:
                        still.append((ref, replica))
                self._outstanding = still
            for replica in finished:
                # shared release path (streaming requests use it too):
                # decrement under self.lock, drain-remove-kill once idle
                self._stream_finished(replica)

    def autoscale_tick(self):
        cfg = self.dep.autoscaling_config
        if cfg is None:
            return
        with self.lock:
            active = [r for r in self.replicas if not r.draining]
            n = len(active)
            avg = sum(r.ongoing for r in active) / max(1, n)
        if avg > cfg.target_ongoing_requests and n < cfg.max_replicas:
            self.add_replica()
        elif avg < cfg.target_ongoing_requests / 2 and n > cfg.min_replicas:
            self.drain_one_replica()

    def close(self):
        with self._watch_cv:
            self._closed = True
            self._watch_cv.notify_all()

    @property
    def num_replicas(self) -> int:
        with self.lock:
            return len([r for r in self.replicas if not r.draining])


class DeploymentHandle:
    """Client handle (serve DeploymentHandle parity): handle.remote(...) or
    handle.method.remote(...)."""

    def __init__(self, rs: _ReplicaSet, method: str = "__call__"):
        self._rs = rs
        self._method = method

    def remote(self, *args, **kwargs):
        return self._rs.submit(self._method, args, kwargs)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._rs, name)

    @property
    def num_replicas(self) -> int:
        return self._rs.num_replicas


_apps: Dict[str, _ReplicaSet] = {}
_routers: Dict[str, Any] = {}
_autoscalers: Dict[str, Any] = {}
_controller_thread: Optional[threading.Thread] = None
_controller_stop = threading.Event()
_http_server = None


def _controller_loop():
    """ServeController reconcile loop (controller.py:121 analog):
    legacy autoscale ticks plus a ~1s replica liveness sweep so dead
    replicas backfill even with no traffic hitting them."""
    ticks = 0
    while not _controller_stop.wait(0.25):
        ticks += 1
        for rs in list(_apps.values()):
            try:
                rs.autoscale_tick()
            except Exception:  # noqa: BLE001
                pass
            if ticks % 4 == 0:
                try:
                    rs.reap_dead_replicas()
                except Exception:  # noqa: BLE001
                    pass


def run(app: Application, *, name: Optional[str] = None) -> DeploymentHandle:
    global _controller_thread
    key = name or app.deployment.name
    if key in _apps:
        return DeploymentHandle(_apps[key])
    rs = _ReplicaSet(app)
    _apps[key] = rs
    # the ingress router fleet (horizontally scaled front door):
    # cfg.serve_routers ServeRouter replicas behind a consistent-hash
    # tenant assignment, sharded admission reconciled to the global
    # budget, token-exact cross-router stream failover. Duck-types the
    # single-router surface, so get_router() callers are unchanged;
    # with serve_routers=1 this IS the old layout plus a one-entry
    # assignment table.
    from .fleet import RouterFleet

    router = RouterFleet(rs)
    _routers[key] = router
    # deployments that declare a stats method (e.g. the LLM servers'
    # serve_stats: engine + prefix-cache counters) get it sampled into
    # the head report, so QueryState("serve") carries engine state too
    extra_stats_fn = None
    if app.deployment.stats_method:
        method = app.deployment.stats_method

        def extra_stats_fn(_rs=rs, _method=method):
            return ray_tpu.get(_rs.submit(_method, (), {}), timeout=5.0)

    router.start_reporting(extra_stats_fn)
    if app.deployment.slo is not None:
        from .slo_autoscaler import SLOAutoscaler

        scaler = SLOAutoscaler(router, app.deployment.slo)
        scaler.start()
        _autoscalers[key] = scaler
    if _controller_thread is None or not _controller_thread.is_alive():
        _controller_stop.clear()
        _controller_thread = threading.Thread(
            target=_controller_loop, name="serve-controller", daemon=True
        )
        _controller_thread.start()
    return DeploymentHandle(rs)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(_apps[name])


def get_router(name: str):
    """The deployment's ingress :class:`~.fleet.RouterFleet` (admission
    + lease-routed dispatch + push-plane streaming + cross-router
    failover). Router-protocol compatible with the old single
    ServeRouter."""
    return _routers[name]


def shutdown() -> None:
    global _http_server, _grpc_server, _proto_grpc_server
    _controller_stop.set()
    for scaler in _autoscalers.values():
        scaler.stop()
    _autoscalers.clear()
    for router in _routers.values():
        router.close()
    _routers.clear()
    from .router import shutdown_sink

    shutdown_sink()
    for rs in _apps.values():
        rs.close()
        for replica in list(rs.replicas):
            try:
                ray_tpu.kill(replica.actor)
            except Exception:  # noqa: BLE001
                pass
    _apps.clear()
    if _http_server is not None:
        _http_server.shutdown()
        _http_server = None
    if _grpc_server is not None:
        _grpc_server.shutdown()
        _grpc_server = None
    if _proto_grpc_server is not None:
        _proto_grpc_server.stop()
        _proto_grpc_server = None


_grpc_server = None
_grpc_lock = threading.Lock()


def start_grpc_ingress(port: int = 0) -> str:
    """gRPC front door (the reference proxies gRPC alongside HTTP,
    serve/_private/proxy.py gRPCProxy): any cluster RpcClient can call
    ServeCall / ServeStreamOpen / ServeStreamNext against the returned
    address. Returns "host:port". Idempotent for the same port; asking
    for a DIFFERENT specific port while one is live is an error rather
    than silently handing back the old address."""
    global _grpc_server
    from .grpc_ingress import GrpcIngress

    with _grpc_lock:
        if _grpc_server is None:
            _grpc_server = GrpcIngress(_apps, port=port)
        elif port and not _grpc_server.address.endswith(f":{port}"):
            raise RuntimeError(
                f"gRPC ingress already listening on {_grpc_server.address}; "
                f"cannot also bind port {port} (call serve.shutdown() first)"
            )
        return _grpc_server.address


_proto_grpc_server = None


def start_proto_grpc_ingress(
    registrations, port: int = 0
) -> str:
    """Protobuf-interop gRPC ingress (reference grpc_util.py gRPCProxy):
    ``registrations`` is a list of ``(add_<Service>Servicer_to_server,
    deployment_name)`` pairs using the user's GENERATED grpc code — any
    grpcio client with its own compiled stubs (no ray_tpu installed)
    calls the deployment's methods; server-streaming methods stream via
    num_returns="streaming" replica calls. Returns "host:port"."""
    global _proto_grpc_server
    from .proto_ingress import ProtoGrpcIngress

    with _grpc_lock:
        if _proto_grpc_server is not None:
            raise RuntimeError(
                "proto gRPC ingress already running at "
                f"{_proto_grpc_server.address}; serve.shutdown() first"
            )
        _proto_grpc_server = ProtoGrpcIngress(
            _apps, list(registrations), port=port
        )
        return _proto_grpc_server.address


def start_http_proxy(port: int = 8000) -> int:
    """HTTP ingress (proxy.py analog): the async aiohttp proxy with SSE
    streaming (ray_tpu/serve/proxy.py) when aiohttp is available; a
    minimal stdlib fallback otherwise."""
    global _http_server
    import importlib.util

    if importlib.util.find_spec("aiohttp") is not None:
        from .proxy import ServeProxy

        _http_server = ServeProxy(_apps, port=port)
        return _http_server.port
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            name = self.path.strip("/").split("/")[0]
            rs = _apps.get(name)
            if rs is None:
                self.send_response(404)
                self.end_headers()
                self.wfile.write(b'{"error": "no such deployment"}')
                return
            length = int(self.headers.get("Content-Length", 0))
            payload = (
                json.loads(self.rfile.read(length)) if length else None
            )
            try:
                ref = rs.submit("__call__", (payload,), {})
                result = ray_tpu.get(ref, timeout=60)
                body = json.dumps({"result": result}).encode()
                self.send_response(200)
            except Exception as exc:  # noqa: BLE001
                body = json.dumps({"error": repr(exc)}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    _http_server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(
        target=_http_server.serve_forever, name="serve-proxy", daemon=True
    ).start()
    return _http_server.server_address[1]
