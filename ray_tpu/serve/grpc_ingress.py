"""gRPC ingress for Serve deployments.

The reference's proxy tier serves BOTH HTTP and gRPC
(/root/reference/python/ray/serve/_private/proxy.py gRPCProxy +
grpc_util.py): gRPC clients reach deployments without the HTTP hop.
Here the ingress rides the framework's generic gRPC layer
(cluster/rpc.py — HTTP/2 wire, name-dispatched handlers), so no .proto
files are needed and any RpcClient is a serve client:

- ``ServeCall {deployment, payload}`` → unary call through the same
  p2c-balanced replica set as handle calls and the HTTP proxy.
- ``ServeStreamOpen {deployment, payload}`` → ``stream_id``; the replica
  runs ``stream_to(writer, payload)`` over the shared transport selection
  (same-host shm ring, cross-host relay actor — serve/proxy.py
  start_stream). ``ServeStreamNext {stream_id, max_items, timeout}``
  drains tokens in order; ``ServeStreamClose`` releases the transport.
  Poll-based streaming keeps the generic unary wire; each Next call is a
  long-poll so tokens flow at RPC latency, not poll cadence.
- ``ServeRoutes`` → deployment names (discovery/probes).
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, Optional

import ray_tpu

from .proxy import _local_hosts, same_host_predicate, start_stream


class _Stream:
    __slots__ = ("ch", "relay", "reader", "ref", "ended", "error", "lock")

    def __init__(self, ch, relay, reader, ref):
        self.ch = ch
        self.relay = relay
        self.reader = reader
        self.ref = ref
        self.ended = False
        self.error = None  # replica exception, re-raised to the client
        self.lock = threading.Lock()  # Next calls for one stream serialize

    def close(self) -> None:
        if self.ch is not None:
            self.ch.destroy()
        if self.relay is not None:
            try:
                ray_tpu.kill(self.relay)
            except Exception:  # noqa: BLE001
                pass


class GrpcIngress:
    """gRPC front door over the live deployment map."""

    STREAM_IDLE_REAP_S = 300.0

    def __init__(self, apps: Dict[str, Any], port: int = 0):
        from ray_tpu.cluster.rpc import RpcServer

        self._apps = apps
        self._streams: Dict[str, tuple] = {}  # id -> (_Stream, last_used)
        self._lock = threading.Lock()
        self._host_cache: dict = {}
        self._hosts = None
        self._server = RpcServer(
            {
                "ServeCall": self._h_call,
                "ServeRoutes": lambda r: sorted(self._apps),
                "ServeStreamOpen": self._h_open,
                "ServeStreamNext": self._h_next,
                "ServeStreamClose": self._h_close,
            },
            port=port,
        )
        self.port = self._server.port
        self.address = self._server.address

    # ------------------------------------------------------------------
    def _rs(self, name: str):
        rs = self._apps.get(name)
        if rs is None:
            raise KeyError(f"no such deployment: {name!r}")
        return rs

    def _h_call(self, req: dict) -> Any:
        rs = self._rs(req["deployment"])
        ref = rs.submit("__call__", (req.get("payload"),), {})
        return ray_tpu.get(ref, timeout=req.get("timeout") or 60.0)

    def _h_open(self, req: dict) -> str:
        rs = self._rs(req["deployment"])
        if self._hosts is None:
            self._hosts = _local_hosts()
        pred = same_host_predicate(self._host_cache, self._hosts)
        ch, relay, reader, ref = start_stream(rs, req.get("payload"), pred)
        sid = uuid.uuid4().hex[:16]
        with self._lock:
            reaped = self._pop_idle_locked()
            self._streams[sid] = (
                _Stream(ch, relay, reader, ref),
                time.monotonic(),
            )
        for stale in reaped:  # blocking closes happen OUTSIDE the lock
            stale.close()
        return sid

    def _h_next(self, req: dict) -> dict:
        from ray_tpu.experimental import ChannelClosed

        sid = req["stream_id"]
        with self._lock:
            entry = self._streams.get(sid)
            if entry is None:
                raise KeyError(f"unknown stream {sid!r}")
            stream = entry[0]
            self._streams[sid] = (stream, time.monotonic())
            reaped = self._pop_idle_locked()
        for stale in reaped:  # a server that stops seeing Opens must
            stale.close()  # still reap vanished clients (r4 advisor)
        max_items = int(req.get("max_items") or 64)
        window = float(req.get("timeout") or 5.0)
        items = []
        deadline = time.monotonic() + window
        with stream.lock:
            if stream.error is not None:
                raise stream.error
            if stream.ended:
                return {"items": [], "ended": True}
            while len(items) < max_items:
                remaining = deadline - time.monotonic()
                if remaining <= 0 and items:
                    break
                try:
                    items.append(
                        stream.reader.read(timeout=max(0.05, remaining))
                    )
                except ChannelClosed:
                    stream.ended = True
                    break
                except TimeoutError:
                    # stalled: did the replica method finish (or die)?
                    from ray_tpu import GetTimeoutError

                    try:
                        ray_tpu.get(stream.ref, timeout=0.05)
                    except GetTimeoutError:
                        break  # still running; client polls again
                    except BaseException as exc:  # noqa: BLE001
                        # replica raised: surface it now and on every
                        # later Next (matching the HTTP relay's _ERR)
                        stream.ended = True
                        stream.error = exc
                        raise
                    # method returned: drain the tail written between
                    # our timeout and the probe (proxy.py relay() race)
                    try:
                        while len(items) < max_items:
                            items.append(stream.reader.read(timeout=0.5))
                        # batch filled with buffer possibly non-empty:
                        # leave ended False so the next poll drains it
                    except (ChannelClosed, TimeoutError):
                        stream.ended = True
                    break
        return {"items": items, "ended": stream.ended}

    def _h_close(self, req: dict) -> None:
        with self._lock:
            entry = self._streams.pop(req["stream_id"], None)
            reaped = self._pop_idle_locked()
        if entry is not None:
            entry[0].close()
        for stale in reaped:
            stale.close()

    def _pop_idle_locked(self) -> list:
        """Collect abandoned streams (client vanished without Close) so
        relay actors / rings don't leak. Caller holds self._lock; the
        returned streams are closed by the caller AFTER releasing it
        (close() does head RPCs)."""
        now = time.monotonic()
        out = []
        for sid, (stream, last) in list(self._streams.items()):
            if now - last > self.STREAM_IDLE_REAP_S:
                self._streams.pop(sid, None)
                out.append(stream)
        return out

    def shutdown(self) -> None:
        with self._lock:
            streams = [s for s, _ in self._streams.values()]
            self._streams.clear()
        for s in streams:
            s.close()
        self._server.stop()
