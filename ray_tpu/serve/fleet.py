"""Horizontally scaled ingress: the router fleet.

PR 12 removed the head as a single point of failure; this module does
the same for the serving-plane front door. One deployment's ingress is
now N :class:`~.router.ServeRouter` replicas behind a consistent-hash
tenant→router assignment:

- **Assignment** — the head owns the member list and a monotone
  *assignment epoch* per deployment (``ServeFleetJoin`` /
  ``ServeFleetLeave``), published via ``QueryState("serve")``. Both
  sides derive the hash ring deterministically from the member ids
  (crc32 virtual nodes — never Python ``hash``), so the head and every
  fleet client agree on ownership without shipping ring state.
- **Sharded admission, global fairness** — each router runs its own
  :class:`~.admission.AdmissionController` token bucket. A reconcile
  loop (``serve_budget_reconcile_s``) reports per-tenant usage/demand
  to the head and receives this router's share of the GLOBAL admission
  rate, split ∝ the summed WFQ weights of the tenants active on it
  (Gavel-style partition+reconcile, arxiv 2008.09213): a weight-3
  tenant drains ~3× a weight-1 tenant even when the two land on
  different routers — weighted fairness is a cluster-wide invariant,
  not a per-process accident (Synergy, arxiv 2110.06073).
- **Token-exact router failover** — every resumable
  :class:`FleetStream`'s delivered count checkpoints into the head's
  replicated stream-lease table (``ShardedTable`` + WAL, PR 12's
  machinery, so a promoted standby inherits the rows). When a router
  dies mid-stream, the sibling inheriting the tenant's hash range
  re-dispatches with ``resume_from=<checkpointed delivered>``; the
  consumer-side skip window discards the (checkpoint .. locally-acked)
  overlap, so acked deltas are neither duplicated nor dropped even
  when the table checkpoint lags the consumer.
- **Epoch fencing** — every acquire/checkpoint/budget RPC is stamped
  with the assignment epoch; a deposed router's late traffic is
  rejected with a typed stale reply (``RouterDeposedError``), mirroring
  the cluster-epoch fence on every other control surface.

Off-cluster (in-process runtime) the same protocol runs against a
:class:`_LocalFleetCoordinator`, so fleet semantics are unit-testable
head-free.
"""
from __future__ import annotations

import threading
import time
import uuid
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.util.metrics import Counter, Gauge, Histogram

from .admission import AdmissionController, controller_from_cfg
from .router import ChannelClosed, RouterKilled, ServeRouter

SERVE_ROUTERS_LIVE = Gauge(
    "serve_routers_live",
    "Live ingress routers in the fleet, per deployment.",
    label_names=("deployment",),
)
SERVE_ROUTER_FAILOVERS = Counter(
    "serve_router_failovers_total",
    "Mid-stream ROUTER failovers (cross-router re-dispatches).",
    label_names=("deployment",),
)
SERVE_ROUTER_FAILOVER_S = Histogram(
    "serve_router_failover_s",
    "Router-death to sibling re-dispatch latency (s).",
    boundaries=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0),
    label_names=("deployment",),
)


class RouterDeposedError(RuntimeError):
    """Epoch fence: the control RPC was stamped with a stale assignment
    epoch — the sender was deposed (its hash ranges moved)."""

    def __init__(self, current_epoch: int, detail: str = ""):
        super().__init__(
            f"stale assignment epoch (current {current_epoch})"
            + (f": {detail}" if detail else "")
        )
        self.current_epoch = int(current_epoch)


# ---------------------------------------------------------------------------
# consistent-hash assignment
# ---------------------------------------------------------------------------
class HashRing:
    """Tenant→router consistent hashing over crc32 virtual nodes.

    Derived purely from ``(members, vnodes)``: the head and every
    client rebuild the identical ring from the published member list —
    stable across processes and restarts (crc32, never Python ``hash``,
    exactly like :func:`~ray_tpu.cluster.shards.shard_of`). Removing a
    member moves ONLY the ranges it owned to the surviving siblings."""

    def __init__(self, members: List[str], vnodes: int = 64):
        self.members = sorted(set(members))
        self.vnodes = max(1, int(vnodes))
        self._ring: List[Tuple[int, str]] = sorted(
            (zlib.crc32(f"{m}#{v}".encode()), m)
            for m in self.members
            for v in range(self.vnodes)
        )

    def owner(self, key: str) -> str:
        if not self._ring:
            raise RuntimeError("hash ring is empty (no live routers)")
        h = zlib.crc32(key.encode() if isinstance(key, str) else key)
        # first vnode clockwise of the key's point (wraps)
        lo, hi = 0, len(self._ring)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._ring[mid][0] < h:
                lo = mid + 1
            else:
                hi = mid
        return self._ring[lo % len(self._ring)][1]


# ---------------------------------------------------------------------------
# budget arithmetic (shared by the head handler and the local coordinator)
# ---------------------------------------------------------------------------
def compute_budget_shares(
    reports: Dict[str, dict],
    qps: float,
    burst: float,
    window_s: float,
) -> Dict[str, dict]:
    """Split the global admission rate across routers ∝ the summed WFQ
    weights of the tenants ACTIVE on each (active = admitted or parked
    demand in the last reconcile window). ``reports`` maps router_id →
    ``{"usage": {tenant: n}, "waiting": {tenant: n},
    "weights": {tenant: w}}``.

    An idle router keeps a small floor share (2% of global) so a cold
    tenant's first burst is not starved for a full reconcile window.
    ``headroom`` says whether the CLUSTER-wide admitted rate is below
    the global budget — the honest retry hint when one shard's bucket
    is dry (see ``AdmissionController.note_global_budget``)."""
    rids = sorted(reports)
    if not rids:
        return {}
    if qps <= 0:
        # unlimited global rate: shards stay unlimited too
        return {
            rid: {"rate": 0.0, "burst": burst, "headroom": True}
            for rid in rids
        }
    weights: Dict[str, float] = {}
    for rep in reports.values():
        weights.update(rep.get("weights") or {})

    def _wt(tenant: str) -> float:
        return max(1e-6, float(weights.get(tenant, 1.0)))

    active_w: Dict[str, float] = {}
    for rid in rids:
        rep = reports[rid]
        active = {
            t for t, n in (rep.get("usage") or {}).items() if n > 0
        } | {t for t, n in (rep.get("waiting") or {}).items() if n > 0}
        active_w[rid] = sum(_wt(t) for t in active)
    total_w = sum(active_w.values())
    used = sum(
        sum((reports[rid].get("usage") or {}).values()) for rid in rids
    )
    headroom = used < qps * max(window_s, 1e-3) * 0.95
    out: Dict[str, dict] = {}
    for rid in rids:
        frac = (
            active_w[rid] / total_w if total_w > 0 else 1.0 / len(rids)
        )
        out[rid] = {
            "rate": max(qps * frac, 0.02 * qps),
            "burst": max(1.0, burst * max(frac, 0.05)),
            "headroom": headroom,
        }
    return out


# ---------------------------------------------------------------------------
# coordinators: who owns the assignment table + stream leases
# ---------------------------------------------------------------------------
class _LocalFleetCoordinator:
    """In-process assignment/lease authority for the off-cluster
    runtime: the exact head protocol (epochs, fencing, stream rows,
    budget shares) against process-local dicts, so every fleet
    code path — including the fences — runs identically in unit
    tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fleets: Dict[str, dict] = {}  # dep -> {"epoch","members"}
        self._streams: Dict[str, dict] = {}  # stream_id -> row
        self._budget: Dict[str, dict] = {}  # dep -> rid -> report

    # -- membership -----------------------------------------------------
    def join(self, deployment: str, router_id: str) -> dict:
        with self._lock:
            f = self._fleets.setdefault(
                deployment, {"epoch": 0, "members": []}
            )
            if router_id not in f["members"]:
                f["members"] = sorted(f["members"] + [router_id])
                f["epoch"] += 1
            return {"epoch": f["epoch"], "members": list(f["members"])}

    def leave(self, deployment: str, router_id: str) -> dict:
        with self._lock:
            f = self._fleets.setdefault(
                deployment, {"epoch": 0, "members": []}
            )
            if router_id in f["members"]:
                f["members"] = [
                    m for m in f["members"] if m != router_id
                ]
                f["epoch"] += 1
            (self._budget.get(deployment) or {}).pop(router_id, None)
            return {"epoch": f["epoch"], "members": list(f["members"])}

    def assignment(self, deployment: str) -> dict:
        with self._lock:
            f = self._fleets.get(deployment) or {
                "epoch": 0,
                "members": [],
            }
            return {"epoch": f["epoch"], "members": list(f["members"])}

    # -- stream leases ---------------------------------------------------
    def _fence_locked(self, deployment: str, epoch: int) -> None:
        f = self._fleets.get(deployment)
        cur = f["epoch"] if f else 0
        if int(epoch) != cur:
            raise RouterDeposedError(cur)

    def stream_acquire(
        self,
        deployment: str,
        router_id: str,
        epoch: int,
        stream_id: str,
        tenant: str,
        delivered: int,
    ) -> dict:
        with self._lock:
            self._fence_locked(deployment, epoch)
            row = self._streams.get(stream_id) or {
                "stream_id": stream_id,
                "deployment": deployment,
                "tenant": tenant,
                "delivered": 0,
            }
            row["router_id"] = router_id
            row["delivered"] = max(
                int(row["delivered"]), int(delivered)
            )
            self._streams[stream_id] = row
            return dict(row)

    def stream_ckpt(
        self,
        deployment: str,
        router_id: str,
        epoch: int,
        ckpts: Dict[str, int],
    ) -> None:
        with self._lock:
            self._fence_locked(deployment, epoch)
            for sid, delivered in ckpts.items():
                row = self._streams.get(sid)
                if row is None or row["router_id"] != router_id:
                    continue  # moved to a sibling: the ckpt is stale
                row["delivered"] = max(
                    int(row["delivered"]), int(delivered)
                )

    def stream_release(self, stream_ids) -> None:
        with self._lock:
            for sid in stream_ids:
                self._streams.pop(sid, None)

    def stream_lookup(self, stream_id: str) -> Optional[dict]:
        with self._lock:
            row = self._streams.get(stream_id)
            return dict(row) if row else None

    # -- budget ----------------------------------------------------------
    def budget(
        self,
        deployment: str,
        router_id: str,
        epoch: int,
        usage: Dict[str, int],
        waiting: Dict[str, int],
        weights: Dict[str, float],
        pressure: Optional[Dict[str, dict]] = None,
    ) -> dict:
        from ray_tpu.config import cfg

        window = max(0.05, float(cfg.serve_budget_reconcile_s))
        with self._lock:
            self._fence_locked(deployment, epoch)
            members = set(
                (self._fleets.get(deployment) or {}).get("members", ())
            )
            reports = self._budget.setdefault(deployment, {})
            reports[router_id] = {
                "usage": dict(usage),
                "waiting": dict(waiting),
                "weights": dict(weights or {}),
                "pressure": dict(pressure or {}),
                "ts": time.monotonic(),
            }
            now = time.monotonic()
            fresh = {
                rid: rep
                for rid, rep in reports.items()
                if rid in members and now - rep["ts"] < 3.0
            }
            shares = compute_budget_shares(
                fresh,
                float(cfg.serve_admission_qps),
                float(cfg.serve_admission_burst),
                window,
            )
            share = shares.get(router_id) or {
                "rate": 0.0,
                "burst": float(cfg.serve_admission_burst),
                "headroom": True,
            }
            hint = _capacity_hint_local(fresh)
        # capacity_hint is ALWAYS present: None is the positive "demand
        # drained" signal that clears the fleet's hold-capacity latch
        # immediately (hold-capacity latch fix) instead of letting a
        # stale blocking hint ride out its staleness window
        reply = {**share, "window_s": window}
        reply["capacity_hint"] = hint
        return reply


def _capacity_hint_local(fresh: Dict[str, dict]) -> Optional[dict]:
    """Serve pressure → capacity hint for the OFF-cluster coordinator:
    the same demand-row kernel path the head runs, against this
    process's CPU count as the lone avail row — so unit tests exercise
    the full pressure→kernel→hint loop without a cluster."""
    try:
        from ray_tpu.scheduler.serve_demand import (
            capacity_plan,
            pressure_rollup,
        )

        pressure = pressure_rollup(fresh)
        if not pressure:
            return None
        import os

        return capacity_plan([float(os.cpu_count() or 1)], pressure)
    except Exception:  # noqa: BLE001 - hint is advisory, never fatal
        return None


class _HeadFleetCoordinator:
    """The on-cluster authority: every call is one head RPC against the
    replicated assignment/stream-lease tables (WAL-persisted, standby-
    mirrored). Stale-epoch replies surface as
    :class:`RouterDeposedError` — the same typed fence the local
    coordinator raises."""

    def __init__(self, rt):
        self._rt = rt

    def _call(self, method: str, req: dict, timeout: float = 5.0):
        reply = self._rt.head.call(method, req, timeout=timeout)
        if isinstance(reply, dict) and reply.get("stale"):
            raise RouterDeposedError(int(reply.get("epoch") or 0), method)
        return reply

    def join(self, deployment: str, router_id: str) -> dict:
        return self._call(
            "ServeFleetJoin",
            {"deployment": deployment, "router_id": router_id},
        )

    def leave(self, deployment: str, router_id: str) -> dict:
        return self._call(
            "ServeFleetLeave",
            {"deployment": deployment, "router_id": router_id},
        )

    def assignment(self, deployment: str) -> dict:
        return self._call("ServeAssignment", {"deployment": deployment})

    def stream_acquire(
        self, deployment, router_id, epoch, stream_id, tenant, delivered
    ) -> dict:
        reply = self._call(
            "ServeStreamAcquire",
            {
                "deployment": deployment,
                "router_id": router_id,
                "epoch": int(epoch),
                "stream_id": stream_id,
                "tenant": tenant,
                "delivered": int(delivered),
            },
        )
        return reply.get("row") or {}

    def stream_ckpt(self, deployment, router_id, epoch, ckpts) -> None:
        self._call(
            "ServeStreamCkpt",
            {
                "deployment": deployment,
                "router_id": router_id,
                "epoch": int(epoch),
                "ckpts": {sid: int(d) for sid, d in ckpts.items()},
            },
        )

    def stream_release(self, stream_ids) -> None:
        self._call(
            "ServeStreamRelease", {"stream_ids": list(stream_ids)}
        )

    def stream_lookup(self, stream_id: str) -> Optional[dict]:
        reply = self._call("ServeStreamLookup", {"stream_id": stream_id})
        return reply.get("row")

    def budget(
        self, deployment, router_id, epoch, usage, waiting, weights,
        pressure=None,
    ) -> dict:
        return self._call(
            "ServeBudget",
            {
                "deployment": deployment,
                "router_id": router_id,
                "epoch": int(epoch),
                "usage": dict(usage),
                "waiting": dict(waiting),
                "weights": dict(weights or {}),
                "pressure": dict(pressure or {}),
            },
        )


def _pick_coordinator():
    try:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        if getattr(rt, "is_remote", False):
            return _HeadFleetCoordinator(rt)
    except Exception:  # noqa: BLE001 - no runtime yet: local authority
        pass
    return _LocalFleetCoordinator()


# ---------------------------------------------------------------------------
# fleet streams (cross-router failover)
# ---------------------------------------------------------------------------
class FleetStream:
    """Consumer view of one tenant stream routed through the fleet:
    ``read()`` yields deltas in order across transports, replica
    failovers (the inner :class:`~.router.RoutedStream`), AND router
    failovers. When the owning router dies, the sibling inheriting the
    tenant's hash range re-dispatches with ``resume_from`` taken from
    the replicated stream-lease checkpoint; the skip window discards
    the (checkpoint .. locally-acked) overlap so the continuation is
    token-exact."""

    def __init__(self, fleet: "RouterFleet", payload, tenant: str):
        self._fleet = fleet
        self._payload = payload
        self.tenant = tenant
        self.stream_id = uuid.uuid4().hex
        self.delivered = 0  # deltas handed to the consumer, fleet-level
        self.router_failovers = 0
        self._skip = 0  # failover overlap still to discard
        self._flushed = 0  # delivered count last checkpointed
        self._released = False
        self._rid, router = fleet._owner(tenant)
        self._leased = fleet.resumable
        if self._leased:
            fleet._stream_acquire(self, self._rid, 0)
        try:
            self._routed = router.stream(payload, tenant)
        except BaseException:
            self._release()
            raise
        fleet._track(self)

    # -- consumption ----------------------------------------------------
    def read(self, timeout: Optional[float] = None):
        while True:
            try:
                value = self._routed.read(timeout=timeout)
            except ChannelClosed:
                self._release()
                raise
            except BaseException as exc:  # noqa: BLE001
                if isinstance(
                    exc, RouterKilled
                ) or self._fleet.is_dead(self._rid):
                    self._failover(exc)
                    continue
                if not isinstance(exc, TimeoutError):
                    self._release()
                raise
            if self._skip > 0:
                # overlap between the table checkpoint we resumed from
                # and what this consumer already acked: discard, exactly
                # once each
                self._skip -= 1
                continue
            self.delivered += 1
            return value

    def __iter__(self):
        while True:
            try:
                yield self.read()
            except ChannelClosed:
                return

    # -- router failover -------------------------------------------------
    def _failover(self, exc: BaseException) -> None:
        from ray_tpu.config import cfg

        fleet = self._fleet
        if not fleet.resumable:
            self._release()
            raise exc
        if self.router_failovers >= int(cfg.serve_stream_failover):
            self._release()
            raise RouterKilled(
                f"stream {self.stream_id[:8]} exhausted "
                f"{self.router_failovers} router failovers"
            ) from exc
        t0 = time.monotonic()
        self.router_failovers += 1
        SERVE_ROUTER_FAILOVERS.inc(labels=fleet._labels)
        try:
            self._routed.close()
        except Exception:  # noqa: BLE001 - corpse-side cleanup
            pass
        fleet._note_router_failure(self._rid)
        # resume point: the replicated checkpoint (what a sibling with
        # NO sight of this consumer would know), clamped by the local
        # acked count; the gap becomes the consumer-side skip window
        ckpt = None
        try:
            row = fleet._coord.stream_lookup(self.stream_id)
            if row is not None:
                ckpt = int(row.get("delivered") or 0)
        except Exception:  # noqa: BLE001 - head mid-failover
            ckpt = None
        resume = (
            min(ckpt, self.delivered) if ckpt is not None else self.delivered
        )
        self._rid, router = fleet._owner(self.tenant)
        if self._leased:
            fleet._stream_acquire(self, self._rid, self.delivered)
        self._skip = self.delivered - resume
        self._routed = router.stream(
            self._payload, self.tenant, resume_base=resume
        )
        SERVE_ROUTER_FAILOVER_S.observe(
            time.monotonic() - t0, labels=fleet._labels
        )

    # -- teardown --------------------------------------------------------
    def _release(self) -> None:
        if self._released:
            return
        self._released = True
        self._fleet._untrack(self)
        if self._leased:
            try:
                self._fleet._coord.stream_release([self.stream_id])
            except Exception:  # noqa: BLE001 - lease GC is best-effort
                pass

    def close(self) -> None:
        try:
            self._routed.close()
        except Exception:  # noqa: BLE001
            pass
        self._release()


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------
class _FleetAdmission:
    """Aggregate admission facade over the live routers' shards (the
    SLO autoscaler and dashboards read one controller-shaped stats
    blob)."""

    def __init__(self, fleet: "RouterFleet"):
        self._fleet = fleet

    def admit(self, tenant: str = "default", timeout_s=None):
        _, router = self._fleet._owner(tenant)
        return router.admission.admit(tenant, timeout_s)

    def stats(self) -> dict:
        shards = [
            (rid, r.admission.stats())
            for rid, r in self._fleet.live_routers()
        ]
        out = {
            "inflight": sum(s["inflight"] for _, s in shards),
            "waiting": sum(s["waiting"] for _, s in shards),
            "admitted": sum(s["admitted"] for _, s in shards),
            "sheds": sum(s["sheds"] for _, s in shards),
            "max_inflight": sum(s["max_inflight"] for _, s in shards),
            "qps_limit": sum(s["qps_limit"] for _, s in shards),
            "shards": {rid: s for rid, s in shards},
        }
        return out


class RouterFleet:
    """N ingress routers over ONE replica set, with consistent-hash
    tenant assignment, head-reconciled admission shards, and
    token-exact cross-router stream failover. Duck-types the single
    :class:`~.router.ServeRouter` surface (``submit``/``call``/
    ``stream``/``stats``/``admission``/``_rs``/``resumable``) so every
    existing caller — proxy, autoscaler, tests — works unchanged; with
    ``serve_routers=1`` the fleet IS the old single-router layout plus
    an assignment table of size one."""

    def __init__(
        self,
        replica_set,
        num_routers: Optional[int] = None,
        coordinator=None,
    ):
        from ray_tpu.config import cfg

        self._rs_ref = replica_set
        self._dep = replica_set.dep.name
        self._labels = {"deployment": self._dep}
        self.resumable = bool(
            getattr(replica_set.dep, "resumable_streams", False)
        )
        self._weights = dict(
            getattr(replica_set.dep, "tenant_weights", None) or {}
        )
        self._coord = (
            coordinator if coordinator is not None else _pick_coordinator()
        )
        self._lock = threading.RLock()
        n = max(1, int(num_routers or cfg.serve_routers))
        self._vnodes = max(1, int(cfg.serve_ring_vnodes))
        self.routers: Dict[str, ServeRouter] = {}
        self.dead: set = set()
        self.epoch = 0
        self._ring: Optional[HashRing] = None
        self._admission_override: Optional[AdmissionController] = None
        self._streams: Dict[str, FleetStream] = {}
        self._closed = False
        self._reconciler: Optional[threading.Thread] = None
        self._reporter: Optional[threading.Thread] = None
        # last scheduler capacity hint from the budget reply (serve
        # pressure fed through the autoscaler kernel); advisory
        self._capacity_hint: Optional[dict] = None
        self._capacity_hint_ts = 0.0
        qps = float(cfg.serve_admission_qps)
        burst = float(cfg.serve_admission_burst)
        for i in range(n):
            rid = f"{self._dep}/r{i}"
            adm = controller_from_cfg(tenant_weights=self._weights)
            if n > 1 and qps > 0:
                # initial even split; the reconcile loop re-splits
                # ∝ active tenant weights within one window
                adm.set_rate(qps / n, max(1.0, burst / n))
            self.routers[rid] = ServeRouter(
                replica_set, admission=adm, router_id=rid
            )
            reply = self._coord.join(self._dep, rid)
            self.epoch = int(reply.get("epoch") or 0)
        self._rebuild_ring()
        SERVE_ROUTERS_LIVE.set(len(self.routers), labels=self._labels)
        self._start_reconciler()

    # -- assignment ------------------------------------------------------
    def _rebuild_ring(self) -> None:
        with self._lock:
            live = sorted(self.routers)
            self._ring = HashRing(live, self._vnodes) if live else None

    def _owner(self, tenant: str) -> Tuple[str, ServeRouter]:
        with self._lock:
            if self._ring is None:
                raise RouterKilled(
                    f"fleet {self._dep} has no live routers"
                )
            rid = self._ring.owner(tenant)
            return rid, self.routers[rid]

    def router_for(self, tenant: str) -> str:
        """The router id currently owning ``tenant`` (assignment
        probe)."""
        return self._owner(tenant)[0]

    def assignment(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "members": sorted(self.routers),
                "dead": sorted(self.dead),
            }

    def _refresh_assignment(self) -> None:
        """Adopt the coordinator's current epoch + member view (after a
        stale-epoch rejection): routers the table no longer lists are
        deposed — their sinks start redirecting and their streams
        re-dispatch through the survivors."""
        try:
            view = self._coord.assignment(self._dep)
        except Exception:  # noqa: BLE001 - head mid-failover
            return
        with self._lock:
            self.epoch = max(self.epoch, int(view.get("epoch") or 0))
            members = set(view.get("members") or ())
            for rid in list(self.routers):
                if rid not in members:
                    router = self.routers.pop(rid)
                    self.dead.add(rid)
                    router.depose(self.epoch)
            self._rebuild_ring()
        SERVE_ROUTERS_LIVE.set(
            len(self.routers), labels=self._labels
        )

    def is_dead(self, rid: str) -> bool:
        with self._lock:
            return rid in self.dead

    def live_routers(self) -> List[Tuple[str, ServeRouter]]:
        with self._lock:
            return sorted(self.routers.items())

    def _note_router_failure(self, rid: str) -> None:
        """A stream observed router ``rid`` dead: make sure the fleet
        and the assignment table agree before re-routing (idempotent —
        chaos_kill_router already did both)."""
        with self._lock:
            router = self.routers.pop(rid, None)
            if router is None:
                return  # already processed
            self.dead.add(rid)
        router.chaos_kill()
        try:
            reply = self._coord.leave(self._dep, rid)
            with self._lock:
                self.epoch = max(
                    self.epoch, int(reply.get("epoch") or 0)
                )
        except Exception:  # noqa: BLE001 - head mid-failover
            pass
        self._rebuild_ring()
        SERVE_ROUTERS_LIVE.set(len(self.routers), labels=self._labels)

    # -- request surface (router protocol) ------------------------------
    def submit(
        self, payload, tenant: str = "default", method: str = "__call__"
    ):
        _, router = self._owner(tenant)
        return router.submit(payload, tenant, method)

    def call(
        self,
        payload,
        tenant: str = "default",
        timeout: float = 60.0,
        method: str = "__call__",
    ):
        return self.submit(payload, tenant, method).result(timeout)

    def stream(self, payload, tenant: str = "default") -> FleetStream:
        return FleetStream(self, payload, tenant)

    # -- stream lease bookkeeping ----------------------------------------
    def _track(self, fs: FleetStream) -> None:
        with self._lock:
            self._streams[fs.stream_id] = fs

    def _untrack(self, fs: FleetStream) -> None:
        with self._lock:
            self._streams.pop(fs.stream_id, None)

    def _stream_acquire(
        self, fs: FleetStream, rid: str, delivered: int
    ) -> None:
        """Register/move one stream's lease row (epoch-fenced). A stale
        epoch triggers one assignment refresh + retry; other failures
        degrade to consumer-local resume (the stream still works, the
        table just lags)."""
        for attempt in (0, 1):
            with self._lock:
                epoch = self.epoch
            try:
                self._coord.stream_acquire(
                    self._dep,
                    rid,
                    epoch,
                    fs.stream_id,
                    fs.tenant,
                    int(delivered),
                )
                fs._flushed = int(delivered)
                return
            except RouterDeposedError:
                if attempt:
                    return
                self._refresh_assignment()
            except Exception:  # noqa: BLE001 - head mid-failover
                return

    def _flush_ckpts(self) -> None:
        """Ship dirty delivered counts into the replicated lease table
        (one batched RPC per owning router per window)."""
        from ray_tpu.config import cfg

        every = max(1, int(cfg.serve_stream_ckpt_every))
        with self._lock:
            epoch = self.epoch
            by_rid: Dict[str, Dict[str, int]] = {}
            for fs in self._streams.values():
                if not fs._leased or fs.delivered - fs._flushed < every:
                    continue
                by_rid.setdefault(fs._rid, {})[
                    fs.stream_id
                ] = fs.delivered
        for rid, ckpts in by_rid.items():
            try:
                self._coord.stream_ckpt(self._dep, rid, epoch, ckpts)
            except RouterDeposedError:
                self._refresh_assignment()
                return
            except Exception:  # noqa: BLE001 - head mid-failover
                return
            with self._lock:
                for sid, delivered in ckpts.items():
                    fs = self._streams.get(sid)
                    if fs is not None:
                        fs._flushed = max(fs._flushed, delivered)

    # -- budget reconciliation -------------------------------------------
    def _start_reconciler(self) -> None:
        def loop():
            from ray_tpu.config import cfg

            while not self._closed:
                time.sleep(
                    max(0.05, float(cfg.serve_budget_reconcile_s))
                )
                try:
                    self._reconcile_once()
                except Exception:  # noqa: BLE001 - must not die
                    pass

        self._reconciler = threading.Thread(
            target=loop, name=f"serve-fleet-{self._dep}", daemon=True
        )
        self._reconciler.start()

    def _reconcile_once(self) -> None:
        from ray_tpu.config import cfg

        self._flush_ckpts()
        with self._lock:
            live = list(self.routers.items())
            epoch = self.epoch
        reconciled = float(cfg.serve_admission_qps) > 0
        for rid, router in live:
            adm = router.admission
            usage = adm.take_usage()
            waiting = adm.waiting_by_tenant()
            # serve pressure export (PR 18): queued prefill tokens +
            # parked requests per tenant ride the budget RPC to the
            # coordinator, which feeds them as demand rows to the
            # autoscaler kernel — the reply's capacity_hint closes the
            # loop back into the SLO autoscaler
            pressure = (
                adm.pressure_by_tenant()
                if hasattr(adm, "pressure_by_tenant")
                else {}
            )
            try:
                reply = self._coord.budget(
                    self._dep, rid, epoch, usage, waiting, self._weights,
                    pressure=pressure,
                )
            except RouterDeposedError:
                self._refresh_assignment()
                return
            except Exception:  # noqa: BLE001 - head mid-failover
                continue
            if not isinstance(reply, dict):
                continue
            window = float(
                reply.get("window_s") or cfg.serve_budget_reconcile_s
            )
            if reconciled and reply.get("rate") is not None:
                adm.set_rate(
                    float(reply["rate"]), float(reply.get("burst") or 1.0)
                )
            adm.note_global_budget(
                bool(reply.get("headroom")), window
            )
            if reply.get("capacity_hint") is not None:
                with self._lock:
                    self._capacity_hint = dict(reply["capacity_hint"])
                    self._capacity_hint_ts = time.monotonic()
            elif self._capacity_hint is not None and (
                "capacity_hint" in reply or self._hint_drained(reply)
            ):
                # hold-capacity latch fix: a reconcile
                # reply carrying hint=None (pressure drained) — or one
                # proving the fleet shrank / this tenant's parked demand
                # emptied — clears the latched blocking hint NOW; the
                # SLO autoscaler must not sit in hold-capacity for up to
                # the full staleness window on a verdict about demand
                # that no longer exists
                with self._lock:
                    self._capacity_hint = None
                    self._capacity_hint_ts = 0.0

    # -- chaos -----------------------------------------------------------
    def chaos_kill_router(self, rid: Optional[str] = None, rng=None):
        """Abruptly kill one live router (chaos ``router_kill``): its
        push endpoint vanishes, its registered streams FAIL, the
        assignment table drops it (epoch bump), and the survivors
        inherit its hash ranges. Returns the victim's id, or None when
        the fleet has a lone router (killing it would be an outage, not
        a failover test)."""
        with self._lock:
            live = sorted(self.routers)
            if len(live) < 2:
                return None
            if rid is None:
                rid = (
                    rng.choice(live)
                    if rng is not None
                    else live[0]
                )
            if rid not in self.routers:
                return None
        self._note_router_failure(rid)
        return rid

    # -- router protocol: observability + lifecycle ----------------------
    @property
    def _rs(self):
        return self._rs_ref

    @property
    def admission(self):
        with self._lock:
            if self._admission_override is not None:
                return self._admission_override
            if len(self.routers) == 1:
                return next(iter(self.routers.values())).admission
        return _FleetAdmission(self)

    @admission.setter
    def admission(self, controller) -> None:
        # test lever (single-router heritage): one shared controller
        # replaces every shard
        with self._lock:
            self._admission_override = controller
            for router in self.routers.values():
                router.admission = controller

    def stats(self) -> dict:
        with self._lock:
            live = sorted(self.routers.items())
        if not live:
            return {
                "deployment": self._dep,
                "codes": {},
                "replicas": [],
                "fleet": self.assignment(),
            }
        base = live[0][1].stats()
        codes: Dict[str, int] = {}
        for _, router in live:
            for code, n in router.stats()["codes"].items():
                codes[code] = codes.get(code, 0) + n
        base["codes"] = codes
        base["admission"] = self.admission.stats()
        base["fleet"] = {
            **self.assignment(),
            "routers": {
                rid: {
                    "codes": r.stats()["codes"],
                    "admission": r.admission.stats(),
                }
                for rid, r in live
            },
            "streams_tracked": len(self._streams),
            "router_failovers": SERVE_ROUTER_FAILOVERS.value(
                self._labels
            ),
            "failover_s": SERVE_ROUTER_FAILOVER_S.summary(self._labels),
            "capacity_hint": self.capacity_hint(),
        }
        return base

    def _hint_drained(self, reply: dict) -> bool:
        """Drain evidence for replies from a coordinator that predates
        the always-present ``capacity_hint`` key: the latched blocking
        hint is moot once this fleet's routers no longer park demand
        (every tenant's waiting queue and pending-token backlog is
        empty) — the verdict described pressure that has drained."""
        try:
            with self._lock:
                live = list(self.routers.values())
            for router in live:
                adm = router.admission
                pressure = (
                    adm.pressure_by_tenant()
                    if hasattr(adm, "pressure_by_tenant")
                    else {}
                )
                for row in (pressure or {}).values():
                    if (
                        int(row.get("waiting") or 0) > 0
                        or int(row.get("waiting_tokens") or 0) > 0
                    ):
                        return False
            return True
        except Exception:  # noqa: BLE001 - advisory path only
            return False

    def capacity_hint(self, max_age_s: float = 10.0) -> Optional[dict]:
        """The scheduler's last serve-pressure capacity verdict (how
        many replica-equivalents the queued demand justifies and
        whether the cluster could place them), or None when stale or
        never reported. SLO autoscalers read it as an upscale
        corroboration signal."""
        with self._lock:
            if (
                self._capacity_hint is None
                or time.monotonic() - self._capacity_hint_ts > max_age_s
            ):
                return None
            return dict(self._capacity_hint)

    def note_ttft_sample(self, ttft_ms: float) -> None:
        for _, router in self.live_routers():
            router.note_ttft_sample(ttft_ms)
            return

    def start_reporting(
        self, extra_stats_fn: Optional[Callable[[], Any]] = None
    ) -> None:
        """One merged 1 Hz report per deployment (router protocol): the
        head's QueryState("serve") carries the fleet block — assignment
        epoch, member list, per-router admission shards."""
        from ray_tpu.config import cfg
        from ray_tpu.core.runtime import get_runtime

        try:
            rt = get_runtime()
        except Exception:  # noqa: BLE001
            return
        if not getattr(rt, "is_remote", False) or self._reporter is not None:
            return

        def loop():
            while not self._closed:
                time.sleep(max(0.1, float(cfg.serve_report_period_s)))
                blob = self.stats()
                if extra_stats_fn is not None:
                    try:
                        blob["engine"] = extra_stats_fn()
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    rt.head.call(
                        "ReportServeState",
                        {
                            "client_id": rt.client_id,
                            "deployment": self._dep,
                            "state": blob,
                        },
                        timeout=5.0,
                    )
                except Exception:  # noqa: BLE001 - head mid-restart
                    pass

        self._reporter = threading.Thread(
            target=loop,
            name=f"serve-report-{self._dep}",
            daemon=True,
        )
        self._reporter.start()

    def close(self) -> None:
        self._closed = True
        with self._lock:
            routers = list(self.routers.items())
            self.routers.clear()
            self._ring = None
        for rid, router in routers:
            try:
                router.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                self._coord.leave(self._dep, rid)
            except Exception:  # noqa: BLE001 - head already gone
                pass
        SERVE_ROUTERS_LIVE.set(0, labels=self._labels)
