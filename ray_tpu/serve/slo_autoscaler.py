"""SLO-driven replica autoscaling for the serving plane.

A control loop on token-throughput/latency metrics rather than raw
``ongoing`` counts (the legacy :class:`AutoscalingConfig` tick): the
scaler watches the router's admitted-in-flight depth and a rolling
window of TTFT observations, and

- **scales up** when in-flight depth sustainedly exceeds
  ``target_queue_per_replica`` per active replica, or the windowed TTFT
  p50 sustainedly violates ``target_ttft_ms`` (when set);
- **scales down** by *graceful drain* when the fleet is sustainedly
  under-utilized: the victim replica stops receiving new requests and
  is killed only once its in-flight streams complete (deployment.py
  drain semantics), so scale-down never cuts a stream mid-token.

New replicas are ordinary actor creations: the head scheduler places
them with the PR 7 heterogeneity-aware multi-objective kernel, so a
mixed fleet puts replicas on the node types that serve them fastest.

Decisions are windowed (``upscale_delay_s`` / ``downscale_delay_s``)
to ride out bursts, and every action is counted in
``serve_autoscale_events_total{direction}``.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.util.metrics import Counter, Gauge, percentile_from_buckets

SERVE_AUTOSCALE_EVENTS = Counter(
    "serve_autoscale_events_total",
    "Serving-plane autoscaling actions.",
    label_names=("direction",),
)
SERVE_REPLICAS = Gauge(
    "serve_replicas",
    "Active (non-draining) replicas per deployment.",
    label_names=("deployment",),
)


@dataclass
class SLOConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ttft_ms: float = 0.0  # 0 = depth-only scaling
    target_queue_per_replica: float = 4.0
    upscale_delay_s: float = 1.0
    downscale_delay_s: float = 5.0

    @classmethod
    def from_cfg(cls, **overrides) -> "SLOConfig":
        from ray_tpu.config import cfg

        base = cls(
            target_ttft_ms=float(cfg.serve_slo_ttft_ms),
            target_queue_per_replica=float(cfg.serve_slo_queue_per_replica),
        )
        for k, v in overrides.items():
            setattr(base, k, v)
        return base


class SLOAutoscaler:
    """One deployment's scaling loop. ``metrics_fn`` is injectable for
    tests: it must return ``{"inflight": int, "replicas": int,
    "ttft_p50_ms": float}``; the default reads the router."""

    def __init__(
        self,
        router,
        slo: Optional[SLOConfig] = None,
        *,
        metrics_fn: Optional[Callable[[], dict]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.router = router
        self.slo = slo or SLOConfig.from_cfg()
        self._clock = clock
        self._metrics_fn = metrics_fn or self._default_metrics
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None
        self._ttft_buckets = None  # last histogram snapshot (window diff)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_decision = "init"
        self.scale_ups = 0
        self.scale_downs = 0
        self.capacity_blocks = 0

    # -- metrics --------------------------------------------------------
    def _default_metrics(self) -> dict:
        from .router import SERVE_TTFT_MS

        rs = self.router._rs
        snap = SERVE_TTFT_MS.buckets_snapshot(
            {"deployment": rs.dep.name}
        )
        if self._ttft_buckets is None:
            window = snap
        else:
            window = [
                max(0, a - b) for a, b in zip(snap, self._ttft_buckets)
            ]
        self._ttft_buckets = snap
        return {
            "inflight": self.router.admission.stats()["inflight"],
            "replicas": rs.num_replicas,
            "ttft_p50_ms": percentile_from_buckets(
                SERVE_TTFT_MS.boundaries, window, 0.50
            ),
        }

    # -- one decision ---------------------------------------------------
    def tick(self) -> str:
        slo = self.slo
        m = self._metrics_fn()
        replicas = max(1, int(m["replicas"]))
        now = self._clock()
        SERVE_REPLICAS.set(
            m["replicas"], labels={"deployment": self.router._rs.dep.name}
        )
        over = m["inflight"] > slo.target_queue_per_replica * replicas or (
            slo.target_ttft_ms > 0
            and m["ttft_p50_ms"] > slo.target_ttft_ms
        )
        under = (
            m["inflight"]
            < 0.5 * slo.target_queue_per_replica * max(1, replicas - 1)
        )
        decision = "hold"
        if over:
            self._under_since = None
            if self._over_since is None:
                self._over_since = now
            elif (
                now - self._over_since >= slo.upscale_delay_s
                and m["replicas"] < slo.max_replicas
            ):
                # Corroborate against the scheduler kernel's serve-
                # pressure verdict when the fleet reconcile has a fresh
                # one: if bin-packing found zero residual room for
                # another replica-shaped row, adding a replica would
                # only oversubscribe the same nodes — hold the window
                # armed and retry next tick instead.
                if self._capacity_blocked():
                    self.capacity_blocks += 1
                    decision = "hold-capacity"
                else:
                    self.router._rs.add_replica()
                    self._over_since = None
                    self.scale_ups += 1
                    SERVE_AUTOSCALE_EVENTS.inc(
                        labels={"direction": "up"}
                    )
                    decision = "up"
        elif under:
            self._over_since = None
            if self._under_since is None:
                self._under_since = now
            elif (
                now - self._under_since >= slo.downscale_delay_s
                and m["replicas"] > slo.min_replicas
            ):
                self.router._rs.drain_one_replica()
                self._under_since = None
                self.scale_downs += 1
                SERVE_AUTOSCALE_EVENTS.inc(labels={"direction": "down"})
                decision = "down"
        else:
            self._over_since = None
            self._under_since = None
        self.last_decision = decision
        return decision

    def _capacity_blocked(self) -> bool:
        """True when a fresh fleet capacity hint (PR 18: per-tenant serve
        pressure pushed through the bin-pack kernel) reports zero
        placeable replica rows. Routers without a fleet — or with a
        stale hint — never block."""
        hint_fn = getattr(self.router, "capacity_hint", None)
        if not callable(hint_fn):
            return False
        try:
            hint = hint_fn()
        except Exception:  # noqa: BLE001 - advisory signal only
            return False
        if not isinstance(hint, dict):
            return False
        try:
            return int(hint.get("replicas_placeable", 1)) <= 0
        except (TypeError, ValueError):
            return False

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        from ray_tpu.config import cfg

        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(
                max(0.05, float(cfg.serve_autoscale_interval_s))
            ):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - scaling must not die
                    pass

        self._thread = threading.Thread(
            target=loop,
            name=f"serve-slo-{self.router._rs.dep.name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def state(self) -> dict:
        hint_fn = getattr(self.router, "capacity_hint", None)
        try:
            hint = hint_fn() if callable(hint_fn) else None
        except Exception:  # noqa: BLE001 - advisory signal only
            hint = None
        return {
            "last_decision": self.last_decision,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "capacity_blocks": self.capacity_blocks,
            "capacity_hint": hint,
            "capacity_blocked": self._capacity_blocked(),
            "min_replicas": self.slo.min_replicas,
            "max_replicas": self.slo.max_replicas,
            "target_ttft_ms": self.slo.target_ttft_ms,
            "target_queue_per_replica": self.slo.target_queue_per_replica,
        }
