"""Lease-routed serving ingress: p2c routing, push-plane streaming.

The request path the serving plane rides end to end:

- **Unary**: admission (:mod:`.admission`) → power-of-two-choices on
  live replica queue depth → the runtime's DIRECT actor channel
  (PR 4's push plane: caller→worker ``DirectPushBatch``, results pushed
  back to the caller's callback server) — a steady request stream makes
  **zero per-request head RPCs** once the per-replica channels are
  warm. The head path remains the automatic fallback (channel death,
  in-process runtime).
- **Streaming**: token deltas never poll. Same-host replicas write the
  shm ring Channel (zero-RPC); cross-host replicas get a
  :class:`PushWriter` that pushes delta batches straight to this
  process's :class:`StreamSink` RPC endpoint — worker→ingress, exactly
  like direct-call result pushes, deprecating the polling
  ``_StreamRelayActor`` (which remains only as the
  ``RAY_TPU_SERVE_PUSH_STREAMS=0`` fallback). Writer-side backpressure
  is depth-based (the push reply carries the buffered depth and the
  cancel flag, so an abandoned stream stops generating instead of
  running to completion).
- **Failover**: a replica SIGKILLed mid-stream fails the transport; if
  the deployment declared its streams resumable (deterministic
  regeneration — the LLM engines are per-request deterministic), the
  router re-dispatches to another replica with
  ``resume_from=<delivered count>`` so acked deltas are neither
  duplicated nor dropped, and reports the death so the replica set
  backfills.
"""
from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Optional, Tuple

import ray_tpu
from ray_tpu.util.metrics import Counter, Gauge, Histogram

from .admission import AdmissionController, Overloaded, controller_from_cfg

_MS_BOUNDS = (1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000)

# every instrument is labeled by deployment: two deployments in one
# process must not contaminate each other's SLO signals or stats.
# serve_requests_total / TTFT / TPOT additionally carry a "model" label
# (multiplexed deployments must not cross-contaminate per-model SLO
# signals). DUAL-OBSERVE convention: the aggregate series (model="") is
# ALWAYS observed — existing readers that pass only {"deployment": d}
# match exactly that series — and a per-model series is observed in
# addition whenever the request carries a model id.
SERVE_REQUESTS = Counter(
    "serve_requests_total",
    "Serving-plane requests by final status code.",
    label_names=("code", "deployment", "model"),
)
SERVE_TTFT_MS = Histogram(
    "serve_ttft_ms",
    "Time to first streamed delta (ms).",
    boundaries=_MS_BOUNDS,
    label_names=("deployment", "model"),
)
SERVE_TPOT_MS = Histogram(
    "serve_tpot_ms",
    "Mean time per output delta after the first (ms), per stream.",
    boundaries=_MS_BOUNDS,
    label_names=("deployment", "model"),
)
SERVE_E2E_MS = Histogram(
    "serve_e2e_ms",
    "End-to-end request latency (ms).",
    boundaries=_MS_BOUNDS,
    label_names=("deployment",),
)
SERVE_LEASE_HITS = Counter(
    "serve_lease_hits_total",
    "Requests dispatched over a live direct (lease) channel.",
    label_names=("deployment",),
)
SERVE_LEASE_MISSES = Counter(
    "serve_lease_misses_total",
    "Requests dispatched before/without a direct channel (head path or "
    "in-process runtime).",
    label_names=("deployment",),
)
SERVE_FAILOVERS = Counter(
    "serve_stream_failovers_total",
    "Mid-stream replica failovers (resume_from re-dispatches).",
    label_names=("deployment",),
)
SERVE_STREAMS = Gauge(
    "serve_streams_active",
    "Token streams currently open at the router.",
    label_names=("deployment",),
)


class ChannelClosed(Exception):
    """Re-exported stream-end signal (kept import-light; the experimental
    Channel's ChannelClosed is a distinct class — readers here normalize
    both to this one)."""


class StreamRedirected(ChannelClosed):
    """Typed redirect: a push landed on a sink that no longer owns the
    stream's hash range (its router was deposed or replaced). The writer
    must stop generating — the fleet re-dispatches the stream on the
    sibling that inherited the range."""

    def __init__(self, msg: str, epoch: int = 0):
        super().__init__(msg)
        self.epoch = int(epoch)


class RouterKilled(RuntimeError):
    """The ingress ROUTER owning this stream died (chaos router_kill /
    abrupt teardown) — not a replica death. Replica-level failover must
    not fire; recovery is fleet-level: the sibling inheriting the
    tenant's hash range re-dispatches with ``resume_from`` taken from
    the replicated stream-lease table."""


def _request_cost(payload) -> int:
    """Approximate prefill cost of a request in tokens (prompt length):
    the admission controller aggregates it per tenant so the fleet's
    budget reconcile can export QUEUED PREFILL TOKENS — not just request
    counts — as scheduler demand pressure."""
    if isinstance(payload, dict):
        prompt = payload.get("prompt")
        if isinstance(prompt, (str, list)):
            return len(prompt)
    return 0


def _is_closed_exc(exc: BaseException) -> bool:
    from ray_tpu.experimental import ChannelClosed as _CC

    return isinstance(exc, (ChannelClosed, _CC))


def _is_replica_death(exc: BaseException) -> bool:
    """Did this dispatch error mean the REPLICA is gone (failover + kill
    + backfill), or did a healthy replica merely raise (the request is
    bad — killing the replica would let one malformed request serially
    destroy the fleet)? TaskError wraps an exception the replica CODE
    raised, so the replica is alive by construction."""
    from ray_tpu.core.object_store import (
        ObjectLostError,
        OwnerDiedError,
        TaskError,
    )
    from ray_tpu.core.runtime import ActorDiedError, NodeDiedError

    if isinstance(exc, TaskError):
        return False
    if isinstance(
        exc, (ActorDiedError, NodeDiedError, ObjectLostError, OwnerDiedError)
    ):
        return True
    text = repr(exc).lower()
    return any(
        k in text for k in ("died", "dead", "unreachable", "lost", "killed")
    )


# ---------------------------------------------------------------------------
# push-plane stream transport (ingress-side sink + picklable writer)
# ---------------------------------------------------------------------------
class _SinkStream:
    """One stream's reassembly buffer at the ingress: batches arrive as
    ``(seq, items, closed)`` (actor-side ordering restored by sequence
    number), readers drain in order. Bounded: a writer that ignores the
    depth contract gets a BufferError back through the push RPC."""

    def __init__(self, max_buffer: int):
        self._stash: Dict[int, tuple] = {}
        self._next_seq = 0
        self._buf: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.cancelled = False
        self._error: Optional[BaseException] = None
        self._max = max_buffer

    def push(self, seq: int, items: list, closed: bool) -> dict:
        with self._cv:
            if self.cancelled:
                return {"depth": len(self._buf), "cancelled": True}
            if len(self._buf) > self._max and not closed:
                raise BufferError(
                    "serve stream sink overrun (consumer stalled and the "
                    "writer ignored backpressure)"
                )
            self._stash[seq] = (items, closed)
            while self._next_seq in self._stash:
                its, cl = self._stash.pop(self._next_seq)
                self._buf.extend(its)
                if cl:
                    self._closed = True
                self._next_seq += 1
            self._cv.notify_all()
            return {"depth": len(self._buf), "cancelled": False}

    def read(self, timeout: Optional[float] = None):
        with self._cv:
            if (
                not self._buf
                and not self._closed
                and not self.cancelled
                and self._error is None
            ):
                self._cv.wait(timeout=timeout if timeout is not None else 5.0)
            if self._buf:
                return self._buf.popleft()
            if self._error is not None:
                # transport failed under the reader (router killed):
                # surface it immediately — waiting out the read window
                # would eat the whole failover budget doing nothing
                raise self._error
            if self._closed or self.cancelled:
                # cancel counts as end-of-stream reader-side too: a
                # blocked reader must not wait out its window (and then
                # misread the cancel-induced replica error as a replica
                # DEATH worth failing over)
                raise ChannelClosed("stream ended")
            raise TimeoutError("no deltas in window")

    def cancel(self) -> None:
        with self._cv:
            self.cancelled = True
            self._cv.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Poison the stream: the next (or a blocked) read raises
        ``exc`` instead of draining the window. Buffered deltas stay
        readable — they were acked to the writer, and the failover
        resume point must count them."""
        with self._cv:
            self._error = exc
            self._cv.notify_all()


class StreamSink:
    """Per-ROUTER push endpoint for token deltas: replica workers RPC
    ``ServeStreamPush`` batches straight here — the streaming analog of
    the direct-call result push plane (no relay actor, no polling, no
    head involvement).

    Fate-shared with its owning router (``router_id``): closing the
    router stops the sink, and a DEPOSED router's sink answers every
    push with a typed redirect (``{"redirect": True}`` →
    :class:`StreamRedirected` writer-side) instead of silently accepting
    deltas for hash ranges it no longer owns."""

    def __init__(self, router_id: str = ""):
        from ray_tpu.cluster.rpc import RpcServer

        self.router_id = router_id
        self._lock = threading.Lock()
        self._streams: Dict[str, _SinkStream] = {}
        self._deposed_epoch: Optional[int] = None
        self._server = RpcServer(
            {"ServeStreamPush": self._h_push, "Ping": lambda r: "pong"},
            port=0,
            max_workers=8,
        )
        self.address = self._server.address

    def open(self) -> Tuple[str, _SinkStream]:
        from ray_tpu.config import cfg

        sid = uuid.uuid4().hex
        stream = _SinkStream(max_buffer=int(cfg.serve_stream_buffer))
        with self._lock:
            self._streams[sid] = stream
        return sid, stream

    def discard(self, sid: str) -> None:
        with self._lock:
            stream = self._streams.pop(sid, None)
        if stream is not None:
            stream.cancel()

    def _h_push(self, req: dict) -> dict:
        with self._lock:
            if self._deposed_epoch is not None:
                # this router lost its hash ranges: a stale replica
                # still pushing here gets a TYPED redirect, never a
                # silent accept into a buffer nobody reads
                return {
                    "redirect": True,
                    "epoch": self._deposed_epoch,
                    "depth": 0,
                    "cancelled": True,
                }
            stream = self._streams.get(req["stream_id"])
        if stream is None:
            # unknown/finished stream: tell the writer to stop generating
            return {"depth": 0, "cancelled": True}
        return stream.push(
            int(req["seq"]), list(req.get("items") or ()), bool(req.get("closed"))
        )

    def depose(self, epoch: int) -> None:
        """The router was replaced at assignment ``epoch``: reject every
        further push with a typed redirect and end the registered
        streams (their consumers re-dispatch through the new owner)."""
        with self._lock:
            self._deposed_epoch = int(epoch)
            streams, self._streams = list(self._streams.values()), {}
        for s in streams:
            s.fail(
                RouterKilled(
                    f"router {self.router_id or '?'} deposed at "
                    f"assignment epoch {epoch}"
                )
            )

    def chaos_kill(self) -> None:
        """Abrupt router death (chaos ``router_kill``): the RPC endpoint
        vanishes mid-push and every registered stream FAILS (not a clean
        close — a killed router's streams must not masquerade as
        complete). Writers see the sink unreachable and stop
        generating, exactly the SIGKILL shape."""
        with self._lock:
            streams, self._streams = list(self._streams.values()), {}
        try:
            self._server.stop()
        except Exception:  # noqa: BLE001 - already down
            pass
        rid = self.router_id or "?"
        for s in streams:
            s.fail(RouterKilled(f"router {rid} killed mid-stream"))

    def stop(self) -> None:
        with self._lock:
            streams = list(self._streams.values())
            self._streams.clear()
        for s in streams:
            s.cancel()
        self._server.stop()


_sink_lock = threading.Lock()
_sink: Optional[StreamSink] = None


def stream_sink() -> StreamSink:
    """Back-compat process-wide sink. Routers own their sinks now
    (``ServeRouter._own_sink`` — fate-shared lifecycle); this singleton
    remains only for callers that predate the fleet."""
    global _sink
    with _sink_lock:
        if _sink is None:
            _sink = StreamSink()
        return _sink


def shutdown_sink() -> None:
    """Tear down the process's push endpoint (serve.shutdown path): the
    RpcServer, its worker threads, and any still-registered streams go
    away; the next stream lazily builds a fresh sink."""
    global _sink
    with _sink_lock:
        sink, _sink = _sink, None
    if sink is not None:
        sink.stop()


class PushWriter:
    """ChannelWriter-compatible handle shipped to a replica: ``write``
    pushes delta batches straight to the ingress StreamSink. The push
    reply's depth throttles the writer and its cancel flag aborts the
    stream (client-disconnect propagation: the replica's generator
    unwinds and the engine reclaims the slot).

    Writes micro-batch adaptively: a delta ships immediately when the
    stream is trickling (keeps TTFT/TPOT at token cadence), but deltas
    produced faster than ``FLUSH_S`` coalesce into one push RPC — a
    fast decode loop is not capped at one token per round trip."""

    THROTTLE_DEPTH = 2048
    FLUSH_S = 0.005
    MAX_BATCH = 64

    def __init__(self, address: str, stream_id: str):
        self._address = address
        self._sid = stream_id
        self._seq = 0
        self._client = None
        self._buf: list = []
        self._last_flush = 0.0

    def _push(self, items: list, closed: bool = False) -> None:
        from ray_tpu.cluster.rpc import RpcClient, RpcError
        from ray_tpu.experimental import ChannelClosed as _CC

        if self._client is None:
            self._client = RpcClient(self._address)
        try:
            reply = self._client.call(
                "ServeStreamPush",
                {
                    "stream_id": self._sid,
                    "seq": self._seq,
                    "items": items,
                    "closed": closed,
                },
                timeout=30.0,
            )
        except RpcError as exc:
            # ingress gone: stop generating (same contract as a closed ring)
            raise _CC(f"serve stream sink unreachable: {exc!r}") from exc
        self._seq += 1
        if reply.get("redirect"):
            raise StreamRedirected(
                "serve stream sink deposed (hash range moved)",
                epoch=int(reply.get("epoch") or 0),
            )
        if reply.get("cancelled") and not closed:
            raise _CC("consumer cancelled the stream")
        depth = int(reply.get("depth") or 0)
        while depth > self.THROTTLE_DEPTH and not closed:
            time.sleep(0.02)
            try:
                reply = self._client.call(
                    "ServeStreamPush",
                    {
                        "stream_id": self._sid,
                        "seq": self._seq,
                        "items": [],
                        "closed": False,
                    },
                    timeout=30.0,
                )
            except RpcError as exc:
                raise _CC(
                    f"serve stream sink unreachable: {exc!r}"
                ) from exc
            self._seq += 1
            if reply.get("redirect"):
                raise StreamRedirected(
                    "serve stream sink deposed (hash range moved)",
                    epoch=int(reply.get("epoch") or 0),
                )
            if reply.get("cancelled"):
                raise _CC("consumer cancelled the stream")
            depth = int(reply.get("depth") or 0)

    def write(self, value, timeout=None) -> None:
        self._buf.append(value)
        now = time.monotonic()
        if (
            now - self._last_flush >= self.FLUSH_S
            or len(self._buf) >= self.MAX_BATCH
        ):
            self._flush(now)

    def _flush(self, now: float) -> None:
        batch, self._buf = self._buf, []
        self._last_flush = now
        self._push(batch)

    def close_channel(self) -> None:
        try:
            batch, self._buf = self._buf, []
            self._push(batch, closed=True)
        except Exception:  # noqa: BLE001 - consumer already gone
            pass

    def close(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001
                pass
            self._client = None

    def __reduce__(self):
        return (PushWriter, (self._address, self._sid))


# ---------------------------------------------------------------------------
# routed streams
# ---------------------------------------------------------------------------
class RoutedStream:
    """Consumer view of one routed token stream: ``read()`` yields
    deltas in order across transports AND across replica failovers;
    ``close()`` releases the admission slot and propagates cancellation
    to the producing replica. Raises :class:`ChannelClosed` at end of
    stream."""

    def __init__(
        self,
        router: "ServeRouter",
        payload,
        tenant: str,
        ticket,
        resume_base: int = 0,
    ):
        self._router = router
        self._payload = payload
        self._ticket = ticket
        self.tenant = tenant
        # deltas already delivered by a PREVIOUS router incarnation
        # (fleet failover): every dispatch resumes past base+delivered,
        # so a replica failover after a router failover still skips the
        # full acked prefix
        self.resume_base = int(resume_base)
        self.delivered = 0
        self.failovers = 0
        self._t0 = time.monotonic()
        self._t0_wall = time.time()
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._finished = False
        self._reader = self._ref = self._replica = None
        self._cleanup = lambda cancelled=False: None
        self._labels = {"deployment": router._rs.dep.name}
        self.model = (
            payload.get("model") if isinstance(payload, dict) else None
        )
        # dual-observe: model-tagged requests additionally land on the
        # per-model series of the model-labeled instruments
        self._mlabels = (
            {**self._labels, "model": str(self.model)} if self.model else None
        )
        SERVE_STREAMS.inc(labels=self._labels)
        try:
            self._attach(router._dispatch_stream(payload, self.resume_base))
        except BaseException:
            self._finish("500")
            raise

    def _attach(self, dispatched) -> None:
        self._reader, self._ref, self._replica, self._cleanup = dispatched

    # -- consumption ----------------------------------------------------
    def read(self, timeout: Optional[float] = None):
        if self._finished:
            raise ChannelClosed("stream closed")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            window = 2.0
            if deadline is not None:
                window = min(window, max(0.05, deadline - time.monotonic()))
            try:
                value = self._reader.read(timeout=window)
            except BaseException as exc:  # noqa: BLE001
                if _is_closed_exc(exc):
                    self._finish("200")
                    raise ChannelClosed("stream ended") from None
                if isinstance(exc, TimeoutError):
                    outcome = self._probe()
                    if outcome is None:  # replica still running
                        if (
                            deadline is not None
                            and time.monotonic() >= deadline
                        ):
                            raise TimeoutError("no deltas in window")
                        continue
                    if outcome == "done":
                        return self._drain_tail()
                    # replica failed mid-stream
                    if self._try_failover(outcome):
                        continue
                    self._finish("500")
                    raise outcome
                # transport trouble (e.g. ring destroyed under us)
                if self._try_failover(exc):
                    continue
                self._finish("500")
                raise
            now = time.monotonic()
            if self._t_first is None:
                self._t_first = now
                ttft = (now - self._t0) * 1000.0
                SERVE_TTFT_MS.observe(ttft, labels=self._labels)
                if self._mlabels:
                    SERVE_TTFT_MS.observe(ttft, labels=self._mlabels)
            self._t_last = now
            self.delivered += 1
            return value

    def __iter__(self):
        while True:
            try:
                yield self.read()
            except ChannelClosed:
                return

    def _probe(self):
        """None = still running; "done" = method returned; an exception
        = the replica call failed (death, raise)."""
        try:
            ray_tpu.get(self._ref, timeout=0.05)
            return "done"
        except ray_tpu.GetTimeoutError:
            return None
        except BaseException as exc:  # noqa: BLE001
            return exc

    def _drain_tail(self):
        """The replica method returned: drain what it wrote between our
        timeout and the probe, then end the stream. A method that
        returned WITHOUT closing its channel is an error, not a clean
        end — a swallowed close would silently truncate the stream."""
        try:
            value = self._reader.read(timeout=0.5)
        except TimeoutError:
            self._finish("500")
            raise RuntimeError(
                "stream_to returned without close_channel() — stream "
                "truncated"
            ) from None
        except BaseException as exc:  # noqa: BLE001
            self._finish("200")
            raise ChannelClosed("stream ended") from (
                None if _is_closed_exc(exc) else exc
            )
        now = time.monotonic()
        if self._t_first is None:
            self._t_first = now
            ttft = (now - self._t0) * 1000.0
            SERVE_TTFT_MS.observe(ttft, labels=self._labels)
            if self._mlabels:
                SERVE_TTFT_MS.observe(ttft, labels=self._mlabels)
        self._t_last = now
        self.delivered += 1
        return value

    # -- failover -------------------------------------------------------
    def _try_failover(self, exc) -> bool:
        from ray_tpu.config import cfg

        if self._finished:
            # consumer already closed (disconnect): the replica error we
            # observed is our own cancellation, not a death worth a
            # re-dispatch — a failover here would leak a sink stream
            # nobody reads and wedge a replica slot generating into it
            return False
        if isinstance(exc, RouterKilled):
            # the ROUTER died, not the replica: replica-level failover
            # would re-dispatch through the corpse. Surface the error —
            # the fleet re-dispatches on the sibling that inherited the
            # tenant's hash range.
            return False
        if isinstance(exc, BaseException) and not _is_replica_death(exc):
            return False  # application error from a healthy replica
        if not self._router.resumable:
            return False
        if self.failovers >= int(cfg.serve_stream_failover):
            return False
        self.failovers += 1
        SERVE_FAILOVERS.inc(labels=self._labels)
        try:
            self._cleanup(cancelled=False)
        except Exception:  # noqa: BLE001
            pass
        self._router._note_replica_failure(self._replica, exc)
        # resume_from = deltas ALREADY HANDED to the consumer (plus any
        # prefix a previous router incarnation delivered): the new
        # replica regenerates deterministically and skips exactly those,
        # so acked deltas are neither repeated nor lost
        self._attach(
            self._router._dispatch_stream(
                self._payload, self.resume_base + self.delivered
            )
        )
        return True

    # -- teardown -------------------------------------------------------
    def _finish(self, code: str) -> None:
        if self._finished:
            return
        self._finished = True
        try:
            # release the transport on EVERY terminal path (end-of-
            # stream included) — a consumer that never calls close()
            # must not leak ring files or sink entries
            self._cleanup(cancelled=False)
        except Exception:  # noqa: BLE001
            pass
        SERVE_STREAMS.dec(labels=self._labels)
        SERVE_REQUESTS.inc(labels={"code": code, **self._labels})
        if self._mlabels:
            SERVE_REQUESTS.inc(labels={"code": code, **self._mlabels})
        SERVE_E2E_MS.observe(
            (time.monotonic() - self._t0) * 1000.0, labels=self._labels
        )
        if (
            self._t_first is not None
            and self._t_last is not None
            and self.delivered > 1
        ):
            tpot = (
                (self._t_last - self._t_first)
                / (self.delivered - 1)
                * 1000.0
            )
            SERVE_TPOT_MS.observe(tpot, labels=self._labels)
            if self._mlabels:
                SERVE_TPOT_MS.observe(tpot, labels=self._mlabels)
        try:
            # request-lifecycle span (ISSUE 15): one slice per stream in
            # the Chrome-trace export, beside the task slices it caused
            from ray_tpu.util.tracing import SPANS

            SPANS.record(
                "serve_stream",
                "serve",
                self._t0_wall,
                time.monotonic() - self._t0,
                pid=f"serve:{self._labels['deployment']}",
                code=code,
                delivered=self.delivered,
                failovers=self.failovers,
                ttft_ms=(
                    (self._t_first - self._t0) * 1000.0
                    if self._t_first is not None
                    else None
                ),
            )
        except Exception:  # noqa: BLE001 - observability only
            pass
        self._router._note_finished(code)
        self._ticket.done()

    def close(self) -> None:
        """Consumer done (or gone): cancel the transport so the replica
        stops generating, release the admission slot."""
        try:
            self._cleanup(cancelled=True)
        except Exception:  # noqa: BLE001
            pass
        self._finish("499")  # no-op if the stream already ended cleanly


# ---------------------------------------------------------------------------
# the router
# ---------------------------------------------------------------------------
class _UnaryRequest:
    def __init__(self, router, ref, ticket, t0, model=None):
        self._router = router
        self.ref = ref
        self._ticket = ticket
        self._t0 = t0
        self._t0_wall = time.time()
        self._done = False
        self._labels = {"deployment": router._rs.dep.name}
        self._mlabels = (
            {**self._labels, "model": str(model)} if model else None
        )

    def result(self, timeout: float = 60.0):
        try:
            value = ray_tpu.get(self.ref, timeout=timeout)
        except ray_tpu.GetTimeoutError:
            # the replica is STILL WORKING: best-effort cancel, and only
            # then release the admission slot — releasing while the work
            # runs would let admission overfill saturated replicas
            try:
                ray_tpu.cancel(self.ref)
            except Exception:  # noqa: BLE001 - cancel is best-effort
                pass
            self._finish("504")
            raise
        except BaseException:
            self._finish("500")
            raise
        self._finish("200")
        return value

    def _finish(self, code: str) -> None:
        if not self._done:
            self._done = True
            SERVE_REQUESTS.inc(labels={"code": code, **self._labels})
            if self._mlabels:
                SERVE_REQUESTS.inc(labels={"code": code, **self._mlabels})
            SERVE_E2E_MS.observe(
                (time.monotonic() - self._t0) * 1000.0,
                labels=self._labels,
            )
            try:
                from ray_tpu.util.tracing import SPANS

                SPANS.record(
                    "serve_unary",
                    "serve",
                    self._t0_wall,
                    time.monotonic() - self._t0,
                    pid=f"serve:{self._labels['deployment']}",
                    code=code,
                )
            except Exception:  # noqa: BLE001 - observability only
                pass
            self._router._note_finished(code)
            self._ticket.done()


class ServeRouter:
    """Per-deployment ingress router over a ``_ReplicaSet``."""

    def __init__(
        self,
        replica_set,
        admission: Optional[AdmissionController] = None,
        router_id: str = "r0",
    ):
        self._rs = replica_set
        self.router_id = router_id
        self.admission = admission or controller_from_cfg()
        self.resumable = bool(
            getattr(replica_set.dep, "resumable_streams", False)
        )
        self._labels = {"deployment": replica_set.dep.name}
        self._stats_lock = threading.Lock()
        self._codes: Dict[str, int] = {}
        # rolling TTFT window for the SLO autoscaler (ts, ttft snapshot
        # via histogram diffing is global; keep a local recent-read list)
        self._recent_ttft: deque = deque(maxlen=256)
        self._host_cache: dict = {}
        self._hosts = None
        self._closed = False
        self.killed = False
        # per-router push sink, built on first streaming dispatch and
        # fate-shared with this router (close/kill/depose) — a replaced
        # router's sink must never keep accepting pushes for streams
        # nobody reads
        self._sink: Optional[StreamSink] = None
        self._sink_lock = threading.Lock()
        self._reporter: Optional[threading.Thread] = None

    # -- unary ----------------------------------------------------------
    def submit(
        self, payload, tenant: str = "default", method: str = "__call__"
    ) -> _UnaryRequest:
        from .deployment import NoReplicasForModel

        model = (
            payload.get("model") if isinstance(payload, dict) else None
        )
        ticket = self.admission.admit(
            tenant, cost=_request_cost(payload)
        )
        t0 = time.monotonic()
        hit = None
        try:
            ref, replica = self._rs.submit_traced(
                method, (payload,), {}, model=model
            )
            hit = self._lease_hit(replica)
        except BaseException as exc:
            ticket.done()
            # per-model empty set is retryable (503), not a server error
            code = "503" if isinstance(exc, NoReplicasForModel) else "500"
            SERVE_REQUESTS.inc(labels={"code": code, **self._labels})
            if model:
                SERVE_REQUESTS.inc(
                    labels={
                        "code": code,
                        **self._labels,
                        "model": str(model),
                    }
                )
            self._note_finished(code)
            raise
        (SERVE_LEASE_HITS if hit else SERVE_LEASE_MISSES).inc(
            labels=self._labels
        )
        return _UnaryRequest(self, ref, ticket, t0, model=model)

    def call(
        self,
        payload,
        tenant: str = "default",
        timeout: float = 60.0,
        method: str = "__call__",
    ):
        return self.submit(payload, tenant, method).result(timeout)

    # -- streaming ------------------------------------------------------
    def stream(
        self, payload, tenant: str = "default", resume_base: int = 0
    ) -> RoutedStream:
        ticket = self.admission.admit(
            tenant, cost=_request_cost(payload)
        )
        try:
            return RoutedStream(
                self, payload, tenant, ticket, resume_base=resume_base
            )
        except Overloaded:
            raise
        except BaseException:
            ticket.done()
            raise

    def _dispatch_stream(self, payload, resume_from: int):
        """Pick transport + replica, dispatch ``stream_to``. Returns
        ``(reader, ref, replica, cleanup(cancelled=...))``."""
        from ray_tpu.config import cfg

        model = (
            payload.get("model") if isinstance(payload, dict) else None
        )
        req = payload
        if resume_from:
            req = dict(payload or {})
            req["resume_from"] = int(resume_from)
        pref_ref = self._maybe_prefill(payload, resume_from, model)
        if pref_ref is not None:
            # ship the prefill result BY REFERENCE nested under a list:
            # only top-level ObjectRef args resolve at dispatch, so the
            # decode replica receives the ref itself and pulls the
            # sealed KV pages over the data plane (land="device") —
            # never through this router
            req = dict(req if resume_from else (payload or {}))
            req["handoff"] = [pref_ref]
        if cfg.serve_shm_streams:
            dispatched = self._try_shm_stream(req, model)
            if dispatched is not None:
                return dispatched
        if cfg.serve_push_streams:
            sink = self._own_sink()
            sid, stream = sink.open()
            writer = PushWriter(sink.address, sid)
            try:
                ref, replica = self._rs.submit_traced(
                    "stream_to", (writer, req), {}, model=model
                )
            except BaseException:
                sink.discard(sid)
                raise
            (
                SERVE_LEASE_HITS
                if self._lease_hit(replica)
                else SERVE_LEASE_MISSES
            ).inc(labels=self._labels)

            def cleanup(cancelled: bool = False, _sid=sid):
                sink.discard(_sid)

            return stream, ref, replica, cleanup
        # legacy polling relay fallback (cross-host, push plane disabled)
        from .proxy import start_stream

        ch, relay_actor, reader, ref = start_stream(
            self._rs, req, self._same_host_pred()
        )

        def cleanup(cancelled: bool = False):
            if relay_actor is not None:
                if cancelled:
                    try:
                        ray_tpu.get(
                            relay_actor.cancel.remote(), timeout=5
                        )
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    ray_tpu.kill(relay_actor)
                except Exception:  # noqa: BLE001
                    pass
            if ch is not None:
                ch.destroy()

        return reader, ref, None, cleanup

    def _maybe_prefill(self, payload, resume_from: int, model):
        """Disaggregated split: when this deployment has a companion
        prefill fleet, run the prefill phase there and return the
        (unresolved) result ref — ``(manifest, k, v)`` with the KV pages
        sealed as device frames. Returns None when disaggregation does
        not apply: monolithic deployment, non-prompt payload, or a
        FAILOVER re-dispatch (``resume_from > 0`` re-prefills locally on
        the sibling — deterministic generation keeps it token-exact,
        and the dead prefill node is out of the path)."""
        pref_name = getattr(self._rs.dep, "prefill_deployment", None)
        if (
            not pref_name
            or resume_from
            or not isinstance(payload, dict)
            or "prompt" not in payload
        ):
            return None
        from .deployment import _apps

        pref_rs = _apps.get(pref_name)
        if pref_rs is None:
            return None
        try:
            ref, _replica = pref_rs.submit_traced(
                "prefill", (dict(payload),), {}, model=model
            )
            return ref
        except Exception:  # noqa: BLE001
            # prefill fleet unavailable (backfill window, dead node):
            # monolithic fallback — the decode replica prefills locally
            return None

    def _try_shm_stream(self, req, model=None):
        """Same-host shm ring (strictly pinned); None when no same-host
        replica exists."""
        from ray_tpu.experimental import Channel

        from .deployment import NoPreferredReplica

        pred = self._same_host_pred()
        with self._rs.lock:
            cands = [r for r in self._rs.replicas if not r.draining] or list(
                self._rs.replicas
            )
        if not any(pred(r) for r in cands):
            return None
        ch = Channel(buffer_size_bytes=1 << 18)
        try:
            ref, replica = self._rs.submit_traced(
                "stream_to",
                (ch.writer, req),
                {},
                prefer=pred,
                strict_prefer=True,
                model=model,
            )
        except NoPreferredReplica:
            ch.destroy()
            return None
        except BaseException:
            ch.destroy()
            raise
        (
            SERVE_LEASE_HITS
            if self._lease_hit(replica)
            else SERVE_LEASE_MISSES
        ).inc(labels=self._labels)

        def cleanup(cancelled: bool = False):
            # destroying the ring flips its closed flag: the replica's
            # next write raises ChannelClosed and generation stops
            ch.destroy()

        return ch.reader, ref, replica, cleanup

    def _own_sink(self) -> StreamSink:
        """This router's push endpoint (lazy — unary-only deployments
        never pay for the RpcServer). Fate-shared: close()/chaos_kill()/
        depose() act on it, unlike the old process-wide singleton whose
        lifetime nobody owned."""
        with self._sink_lock:
            if self._sink is None:
                if self._closed:
                    raise RouterKilled(
                        f"router {self.router_id} is closed"
                    )
                self._sink = StreamSink(router_id=self.router_id)
            return self._sink

    def _same_host_pred(self):
        from .proxy import _local_hosts, same_host_predicate

        if self._hosts is None:
            self._hosts = _local_hosts()
        return same_host_predicate(self._host_cache, self._hosts)

    # -- bookkeeping ----------------------------------------------------
    def _lease_hit(self, replica) -> bool:
        """Did this dispatch ride a live direct channel (zero head RPCs)
        rather than warming one / falling back to the head path?"""
        if replica is None:
            return False
        try:
            from ray_tpu.core.runtime import get_runtime

            rt = get_runtime()
            if not getattr(rt, "is_remote", False):
                return False
            aid = getattr(replica.actor, "_actor_id", None)
            chan = rt._direct_channels.get(aid) if aid else None
            return chan is not None and not getattr(chan, "_dead", False)
        except Exception:  # noqa: BLE001
            return False

    def _note_replica_failure(self, replica, exc) -> None:
        if replica is not None:
            self._rs.note_replica_death(replica)

    def _note_finished(self, code: str) -> None:
        with self._stats_lock:
            self._codes[code] = self._codes.get(code, 0) + 1

    def note_ttft_sample(self, ttft_ms: float) -> None:
        with self._stats_lock:
            self._recent_ttft.append((time.monotonic(), ttft_ms))

    # -- observability ---------------------------------------------------
    def stats(self) -> dict:
        with self._stats_lock:
            codes = dict(self._codes)
        with self._rs.lock:
            replicas = [
                {
                    "actor_id": getattr(r.actor, "_actor_id", None),
                    "ongoing": r.ongoing,
                    "draining": r.draining,
                    "model": r.model,
                }
                for r in self._rs.replicas
            ]
        hits = SERVE_LEASE_HITS.value(self._labels)
        misses = SERVE_LEASE_MISSES.value(self._labels)
        return {
            "deployment": self._rs.dep.name,
            "router_id": self.router_id,
            "replicas": replicas,
            "codes": codes,
            "admission": self.admission.stats(),
            "lease_hits": hits,
            "lease_misses": misses,
            "lease_hit_rate": (
                round(hits / (hits + misses), 4) if hits + misses else None
            ),
            "ttft_ms": SERVE_TTFT_MS.summary(self._labels),
            "e2e_ms": SERVE_E2E_MS.summary(self._labels),
            "streams_active": SERVE_STREAMS.value(self._labels),
            "failovers": SERVE_FAILOVERS.value(self._labels),
            "resumable": self.resumable,
        }

    def start_reporting(self, extra_stats_fn=None) -> None:
        """Periodic serve-state report to the head (control-plane
        cadence; powers head QueryState("serve")). No-op off-cluster."""
        from ray_tpu.config import cfg
        from ray_tpu.core.runtime import get_runtime

        try:
            rt = get_runtime()
        except Exception:  # noqa: BLE001
            return
        if not getattr(rt, "is_remote", False) or self._reporter is not None:
            return

        def loop():
            while not self._closed:
                time.sleep(max(0.1, float(cfg.serve_report_period_s)))
                blob = self.stats()
                if extra_stats_fn is not None:
                    try:
                        blob["engine"] = extra_stats_fn()
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    rt.head.call(
                        "ReportServeState",
                        {
                            "client_id": rt.client_id,
                            "deployment": self._rs.dep.name,
                            "state": blob,
                        },
                        timeout=5.0,
                    )
                except Exception:  # noqa: BLE001 - head mid-restart
                    pass

        self._reporter = threading.Thread(
            target=loop, name=f"serve-report-{self._rs.dep.name}", daemon=True
        )
        self._reporter.start()

    def close(self) -> None:
        """Graceful teardown; the sink fate-shares (satellite of the old
        leaked-singleton bug: a replaced router's sink kept accepting
        pushes forever)."""
        self._closed = True
        with self._sink_lock:
            sink, self._sink = self._sink, None
        if sink is not None:
            sink.stop()

    def depose(self, epoch: int) -> None:
        """This router lost its hash ranges at assignment ``epoch``:
        further pushes get a typed redirect, registered streams end with
        :class:`RouterKilled` so their consumers re-dispatch through the
        new owner."""
        self._closed = True
        with self._sink_lock:
            sink = self._sink
        if sink is not None:
            sink.depose(epoch)

    def chaos_kill(self) -> None:
        """Abrupt death for chaos ``router_kill``: the push endpoint
        vanishes, in-flight streams FAIL (no clean close), admission
        state is lost with the process — the SIGKILL shape for an
        in-process router."""
        self.killed = True
        self._closed = True
        with self._sink_lock:
            sink, self._sink = self._sink, None
        if sink is not None:
            sink.chaos_kill()
