"""Async HTTP ingress for Serve deployments.

The analog of the reference's proxy tier
(/root/reference/python/ray/serve/_private/proxy.py — an ASGI/aiohttp
server routing HTTP to replica handles): an aiohttp application that

- routes ``POST /<deployment>`` to the deployment's ``__call__`` through
  the same replica-set balancing as handle calls (blocking object-plane
  waits run in an executor so the event loop keeps multiplexing),
- streams ``POST /<deployment>/stream`` as Server-Sent Events: the
  replica writes values into a mutable-object Channel
  (ray_tpu.experimental) via its ``stream_to(writer, payload)`` method
  and the proxy relays them as they arrive — token streaming for the
  LLM tier rides this end to end,
- serves ``GET /-/healthz`` and ``GET /-/routes`` for probes/discovery.

Runs on a dedicated thread with its own event loop; the stdlib fallback
in deployment.py remains for environments without aiohttp.
"""
from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import ray_tpu


class ServeProxy:
    def __init__(self, apps: dict, port: int = 0):
        self._apps = apps
        self._port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner = None
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="proxy-wait"
        )
        self._started = threading.Event()
        self.port: Optional[int] = None
        self._startup_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="serve-proxy", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=30) or self.port is None:
            raise RuntimeError(
                f"serve proxy failed to start: {self._startup_error!r}"
            )

    # -- handlers -------------------------------------------------------
    async def _call(self, request):
        from aiohttp import web

        name = request.match_info["deployment"]
        rs = self._apps.get(name)
        if rs is None:
            return web.json_response(
                {"error": "no such deployment"}, status=404
            )
        payload = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
        loop = asyncio.get_running_loop()
        try:
            ref = rs.submit("__call__", (payload,), {})
            result = await loop.run_in_executor(
                self._pool, lambda: ray_tpu.get(ref, timeout=60)
            )
            return web.json_response({"result": result})
        except Exception as exc:  # noqa: BLE001 - errors are responses
            return web.json_response({"error": repr(exc)}, status=500)

    async def _stream(self, request):
        from aiohttp import web

        from ray_tpu.experimental import Channel, ChannelClosed

        name = request.match_info["deployment"]
        rs = self._apps.get(name)
        if rs is None:
            return web.json_response(
                {"error": "no such deployment"}, status=404
            )
        payload = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        ch = Channel(buffer_size_bytes=1 << 18)
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        _END, _ERR = object(), object()
        # bounded handoff: a stalled HTTP client must throttle the relay,
        # which stops draining the ring, which blocks the replica's
        # writer — end-to-end backpressure instead of unbounded proxy RSS
        credits = threading.Semaphore(64)
        dead = threading.Event()

        def relay(ref) -> None:
            """Dedicated per-stream thread: blocking channel reads never
            occupy the shared unary-call pool (32 long streams would
            otherwise starve every other request)."""
            from ray_tpu import GetTimeoutError

            def emit(kind, value=None):
                while not credits.acquire(timeout=1.0):
                    if dead.is_set():
                        raise ChannelClosed("consumer gone")
                loop.call_soon_threadsafe(q.put_nowait, (kind, value))

            try:
                while True:
                    try:
                        value = ch.reader.read(timeout=5)
                    except ChannelClosed:
                        emit(_END)
                        return
                    except TimeoutError:
                        # stalled: is the replica still running?
                        try:
                            ray_tpu.get(ref, timeout=0.1)
                        except GetTimeoutError:
                            continue  # still running; keep waiting
                        except BaseException as exc:  # noqa: BLE001
                            emit(_ERR, repr(exc))  # replica raised
                            return
                        # method returned: drain the tail the replica may
                        # have written between our timeout and the probe
                        try:
                            while True:
                                emit("data", ch.reader.read(timeout=0.5))
                        except ChannelClosed:
                            emit(_END)
                        except TimeoutError:
                            emit(
                                _ERR,
                                "stream_to returned without "
                                "close_channel()",
                            )
                        return
                    emit("data", value)
            except BaseException as exc:  # noqa: BLE001
                if not dead.is_set():
                    emit(_ERR, repr(exc))

        try:
            ref = rs.submit("stream_to", (ch.writer, payload), {})
            threading.Thread(
                target=relay, args=(ref,), name="sse-relay", daemon=True
            ).start()
            while True:
                kind, value = await q.get()
                credits.release()
                if kind is _END:
                    await resp.write(b"event: end\ndata: {}\n\n")
                    break
                if kind is _ERR:
                    await resp.write(
                        f"event: error\ndata: "
                        f"{json.dumps(value)}\n\n".encode()
                    )
                    break
                await resp.write(f"data: {json.dumps(value)}\n\n".encode())
        except Exception as exc:  # noqa: BLE001
            await resp.write(
                f"event: error\ndata: {json.dumps(repr(exc))}\n\n".encode()
            )
        finally:
            dead.set()
            ch.destroy()
        await resp.write_eof()
        return resp

    async def _healthz(self, request):
        from aiohttp import web

        return web.json_response(
            {
                "status": "ok",
                "deployments": {
                    name: len(rs.replicas)
                    for name, rs in self._apps.items()
                },
            }
        )

    async def _routes(self, request):
        from aiohttp import web

        return web.json_response(sorted(self._apps))

    # -- lifecycle ------------------------------------------------------
    def _run(self) -> None:
        try:
            from aiohttp import web
        except BaseException as exc:  # noqa: BLE001 - surfaced to __init__
            self._startup_error = exc
            self._started.set()
            return

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_get("/-/healthz", self._healthz)
        app.router.add_get("/-/routes", self._routes)
        app.router.add_post("/{deployment}/stream", self._stream)
        app.router.add_post("/{deployment}", self._call)

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", self._port)
            await site.start()
            self._runner = runner
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()

        try:
            loop.run_until_complete(start())
        except BaseException as exc:  # noqa: BLE001 - bind failure etc.
            self._startup_error = exc
            self._started.set()
            return
        loop.run_forever()

    def shutdown(self) -> None:
        loop = self._loop
        if loop is None:
            return

        async def stop():
            if self._runner is not None:
                await self._runner.cleanup()
            loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(stop(), loop)
            self._thread.join(timeout=5)
        except RuntimeError:
            pass
        self._pool.shutdown(wait=False)
