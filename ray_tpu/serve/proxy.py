"""Async HTTP ingress for Serve deployments.

The analog of the reference's proxy tier
(/root/reference/python/ray/serve/_private/proxy.py — an ASGI/aiohttp
server routing HTTP to replica handles): an aiohttp application that

- routes ``POST /<deployment>`` to the deployment's ``__call__`` through
  the same replica-set balancing as handle calls (blocking object-plane
  waits run in an executor so the event loop keeps multiplexing),
- streams ``POST /<deployment>/stream`` as Server-Sent Events: the
  replica writes values into a mutable-object Channel
  (ray_tpu.experimental) via its ``stream_to(writer, payload)`` method
  and the proxy relays them as they arrive — token streaming for the
  LLM tier rides this end to end,
- serves ``GET /-/healthz`` and ``GET /-/routes`` for probes/discovery.

Runs on a dedicated thread with its own event loop; the stdlib fallback
in deployment.py remains for environments without aiohttp.
"""
from __future__ import annotations

import asyncio
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import ray_tpu


class _StreamRelayActor:
    """DEPRECATED polling bridge for cross-host streaming, kept only as
    the ``RAY_TPU_SERVE_PUSH_STREAMS=0`` fallback: the default cross-host
    transport is the router's :class:`~ray_tpu.serve.router.StreamSink`
    push plane (replica pushes straight to the ingress process — no
    relay actor, no ``pop`` long-poll). Where the relay remains, its
    buffers are hard-bounded and ``cancel`` propagates client
    disconnects back to the writer so an abandoned stream stops
    generating instead of running to completion."""

    MAX_STASH = 1024  # out-of-order reassembly bound (seq -> batch)

    def __init__(self, max_buffer: int = 4096):
        from collections import deque

        self._stash: dict = {}  # seq -> (items, closed)
        self._next_seq = 0
        self._out = deque()
        self._closed = False
        self._cancelled = False
        self._max = max_buffer
        self._event = None  # created lazily on the actor's event loop

    def _ev(self):
        import asyncio

        if self._event is None:
            self._event = asyncio.Event()
        return self._event

    async def push(self, seq: int, items: list, closed: bool = False) -> int:
        """Returns the current queue depth, or -1 once the consumer
        cancelled (the writer must stop). Backpressure is writer-side
        (throttle on the returned depth) — parking here would hold the
        actor's concurrency slots and starve pop(). A writer that ignores
        the depth contract hits the hard bounds below: the push fails,
        the stream dies, memory stays bounded."""
        if self._cancelled:
            return -1
        if len(self._out) > 4 * self._max and not closed:
            raise BufferError(
                "stream relay buffer overrun (consumer stalled and the "
                "writer ignored backpressure)"
            )
        if len(self._stash) > self.MAX_STASH:
            raise BufferError(
                "stream relay reassembly overrun (sequence gap never "
                "filled while the writer kept pushing)"
            )
        self._stash[seq] = (items, closed)
        while self._next_seq in self._stash:
            its, cl = self._stash.pop(self._next_seq)
            self._out.extend(its)
            if cl:
                self._closed = True
            self._next_seq += 1
        self._ev().set()
        return len(self._out)

    async def cancel(self) -> None:
        """Client disconnected: drop buffered items and tell the writer
        (via the -1 push reply) to abandon generation."""
        self._cancelled = True
        self._closed = True
        self._out.clear()
        self._stash.clear()
        self._ev().set()

    async def depth(self) -> int:
        return -1 if self._cancelled else len(self._out)

    async def pop(self, max_items: int = 256, timeout: float = 5.0):
        """Returns (items, ended). ended only once the queue is drained."""
        import asyncio

        if not self._out and not self._closed:
            self._ev().clear()
            try:
                await asyncio.wait_for(self._ev().wait(), timeout)
            except asyncio.TimeoutError:
                pass
        items = []
        while self._out and len(items) < max_items:
            items.append(self._out.popleft())
        return items, self._closed and not self._out


class _RelayWriter:
    """ChannelWriter-compatible handle shipped to a cross-host replica:
    ``write``/``close_channel`` become actor pushes with a bounded
    in-flight window (ordering restored actor-side by sequence number)."""

    def __init__(self, actor):
        self._actor = actor
        self._seq = 0
        self._pending = []

    def write(self, value, timeout=None) -> None:
        import time as _time

        from ray_tpu.experimental import ChannelClosed

        ref = self._actor.push.remote(self._seq, [value])
        self._seq += 1
        self._pending.append(ref)
        if len(self._pending) > 32:
            depth = ray_tpu.get(self._pending.pop(0), timeout=30)
            if depth < 0:  # consumer cancelled: abandon generation
                raise ChannelClosed("consumer cancelled the stream")
            # a stalled consumer shows up as queue depth: throttle here
            # (writer-side) instead of parking inside the actor
            while depth > 4096:
                _time.sleep(0.05)
                depth = ray_tpu.get(self._actor.depth.remote(), timeout=30)
                if depth < 0:
                    raise ChannelClosed("consumer cancelled the stream")

    def close_channel(self) -> None:
        refs = self._pending + [self._actor.push.remote(self._seq, [], True)]
        self._seq += 1
        self._pending = []
        for r in refs:
            try:
                ray_tpu.get(r, timeout=30)
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        pass

    def __reduce__(self):
        return (_RelayWriter, (self._actor,))


class _RelayReader:
    """ChannelReader-compatible proxy-side view of a _StreamRelayActor."""

    def __init__(self, actor):
        from collections import deque

        self._actor = actor
        self._buf = deque()
        self._ended = False

    def read(self, timeout=None):
        from ray_tpu.experimental import ChannelClosed

        if self._buf:
            return self._buf.popleft()
        if self._ended:
            raise ChannelClosed("stream ended")
        window = timeout if timeout is not None else 5.0
        items, ended = ray_tpu.get(
            self._actor.pop.remote(256, window), timeout=window + 30
        )
        self._buf.extend(items)
        self._ended = ended
        if self._buf:
            return self._buf.popleft()
        if self._ended:
            raise ChannelClosed("stream ended")
        raise TimeoutError("no items in window")


def start_stream(rs, payload, same_host_pred):
    """Blocking transport selection + dispatch shared by the HTTP and gRPC
    ingresses. Returns (ch, relay_actor, reader, ref); on error every
    partially-created resource is cleaned up before the exception
    propagates. Same-host replicas get the shm ring (strictly pinned — a
    same-host-only writer must never reach a cross-host replica); with
    only cross-host replicas a relay actor bridges the tokens."""
    from ray_tpu.experimental import Channel
    from ray_tpu.serve.deployment import NoPreferredReplica

    with rs.lock:
        cands = [r for r in rs.replicas if not r.draining] or list(
            rs.replicas
        )
    if any(same_host_pred(r) for r in cands):
        ch = Channel(buffer_size_bytes=1 << 18)
        try:
            ref = rs.submit(
                "stream_to",
                (ch.writer, payload),
                {},
                prefer=same_host_pred,
                strict_prefer=True,
            )
            return ch, None, ch.reader, ref
        except NoPreferredReplica:
            ch.destroy()
        except BaseException:
            ch.destroy()
            raise
    relay_actor = ray_tpu.remote(_StreamRelayActor).options(
        num_cpus=0.0, max_concurrency=16
    ).remote()
    try:
        ref = rs.submit(
            "stream_to", (_RelayWriter(relay_actor), payload), {}
        )
    except BaseException:
        try:
            ray_tpu.kill(relay_actor)
        except Exception:  # noqa: BLE001
            pass
        raise
    return None, relay_actor, _RelayReader(relay_actor), ref


def same_host_predicate(hosts_cache: dict, local_hosts: Optional[set]):
    """Factory shared by ingresses: predicate over _Replica deciding
    same-host-ness, with per-actor results cached in ``hosts_cache``."""
    from ray_tpu.core.runtime import get_runtime

    try:
        rt = get_runtime()
    except Exception:  # noqa: BLE001
        return lambda r: True
    if not getattr(rt, "is_remote", False):
        return lambda r: True
    local = local_hosts if local_hosts is not None else _local_hosts()

    def pred(replica) -> bool:
        aid = getattr(replica.actor, "_actor_id", None)
        if aid is None:
            return True
        if aid not in hosts_cache:
            _, addr = rt.actor_location(aid)
            host = addr.rsplit(":", 1)[0] if addr else None
            if host is None:
                return False  # unknown ⇒ not-local; relay path is safe
            hosts_cache[aid] = host in local
        return hosts_cache[aid]

    return pred


def _local_hosts() -> set:
    import socket

    hosts = {"127.0.0.1", "localhost", "::1"}
    try:
        hosts.add(socket.gethostname())
        hosts.add(socket.getfqdn())
        hosts.update(
            i[4][0] for i in socket.getaddrinfo(socket.gethostname(), None)
        )
    except OSError:
        pass
    return hosts


class ServeProxy:
    def __init__(self, apps: dict, port: int = 0):
        self._apps = apps
        self._port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner = None
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="proxy-wait"
        )
        self._started = threading.Event()
        self._hosts: Optional[set] = None  # lazy _local_hosts()
        self._host_cache: dict = {}  # actor id -> is-local (sticky)
        self.port: Optional[int] = None
        self._startup_error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="serve-proxy", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=30) or self.port is None:
            raise RuntimeError(
                f"serve proxy failed to start: {self._startup_error!r}"
            )

    def _same_host_pred(self):
        """Replica same-host predicate with proxy-level caching (the head
        RPC happens once per replica, not once per request); callers run
        it on the worker pool, never the event loop."""
        if self._hosts is None:
            self._hosts = _local_hosts()
        return same_host_predicate(self._host_cache, self._hosts)

    def _start_stream(self, rs, payload):
        return start_stream(rs, payload, self._same_host_pred())

    # -- handlers -------------------------------------------------------
    async def _call(self, request):
        from aiohttp import web

        from .admission import Overloaded
        from .deployment import _routers

        name = request.match_info["deployment"]
        rs = self._apps.get(name)
        if rs is None:
            return web.json_response(
                {"error": "no such deployment"}, status=404
            )
        payload = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
        loop = asyncio.get_running_loop()
        tenant = request.headers.get("X-Serve-Tenant", "default")
        router = _routers.get(name)
        try:
            if router is not None:
                # admission + p2c + direct-channel dispatch + metrics
                req = await loop.run_in_executor(
                    self._pool, lambda: router.submit(payload, tenant)
                )
                result = await loop.run_in_executor(
                    self._pool, lambda: req.result(60)
                )
            else:
                ref = rs.submit("__call__", (payload,), {})
                result = await loop.run_in_executor(
                    self._pool, lambda: ray_tpu.get(ref, timeout=60)
                )
            return web.json_response({"result": result})
        except Overloaded as exc:
            return web.json_response(
                {"error": str(exc), "reason": exc.reason},
                status=503,
                headers={"Retry-After": f"{exc.retry_after_s:.2f}"},
            )
        except Exception as exc:  # noqa: BLE001 - errors are responses
            return web.json_response({"error": repr(exc)}, status=500)

    async def _stream(self, request):
        from aiohttp import web

        from .admission import Overloaded
        from .deployment import _routers
        from .router import ChannelClosed as RoutedClosed

        name = request.match_info["deployment"]
        rs = self._apps.get(name)
        if rs is None:
            return web.json_response(
                {"error": "no such deployment"}, status=404
            )
        payload = None
        if request.can_read_body:
            try:
                payload = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
        loop = asyncio.get_running_loop()
        tenant = request.headers.get("X-Serve-Tenant", "default")
        router = _routers.get(name)
        if router is None:
            return web.json_response(
                {"error": "deployment has no router"}, status=500
            )
        # admission BEFORE the SSE response exists: overload is a real
        # 503 with Retry-After, not an error event on an accepted stream
        try:
            stream = await loop.run_in_executor(
                self._pool, lambda: router.stream(payload, tenant)
            )
        except Overloaded as exc:
            return web.json_response(
                {"error": str(exc), "reason": exc.reason},
                status=503,
                headers={"Retry-After": f"{exc.retry_after_s:.2f}"},
            )
        except Exception as exc:  # noqa: BLE001
            return web.json_response({"error": repr(exc)}, status=500)
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        _END, _ERR = object(), object()
        # bounded handoff: a stalled HTTP client must throttle the relay
        # thread, which stops draining the transport, which backpressures
        # the replica's writer — end-to-end instead of unbounded RSS
        credits = threading.Semaphore(64)
        dead = threading.Event()

        def relay() -> None:
            """Dedicated per-stream thread: RoutedStream.read handles
            transport waits, replica probing, and mid-stream failover;
            blocking reads never occupy the shared unary-call pool."""

            def emit(kind, value=None):
                while not credits.acquire(timeout=1.0):
                    if dead.is_set():
                        raise RoutedClosed("consumer gone")
                loop.call_soon_threadsafe(q.put_nowait, (kind, value))

            try:
                while True:
                    try:
                        value = stream.read(timeout=300.0)
                    except RoutedClosed:
                        emit(_END)
                        return
                    except BaseException as exc:  # noqa: BLE001
                        emit(_ERR, repr(exc))
                        return
                    emit("data", value)
            except BaseException as exc:  # noqa: BLE001
                if not dead.is_set():
                    emit(_ERR, repr(exc))

        try:
            threading.Thread(
                target=relay, name="sse-relay", daemon=True
            ).start()
            while True:
                kind, value = await q.get()
                credits.release()
                if kind is _END:
                    await resp.write(b"event: end\ndata: {}\n\n")
                    break
                if kind is _ERR:
                    await resp.write(
                        f"event: error\ndata: "
                        f"{json.dumps(value)}\n\n".encode()
                    )
                    break
                await resp.write(f"data: {json.dumps(value)}\n\n".encode())
        except Exception as exc:  # noqa: BLE001
            await resp.write(
                f"event: error\ndata: {json.dumps(repr(exc))}\n\n".encode()
            )
        finally:
            dead.set()
            # close() cancels the transport (sink discard / ring destroy
            # / relay cancel), so a disconnected client's replica stops
            # generating instead of running to completion
            stream.close()
        await resp.write_eof()
        return resp

    async def _healthz(self, request):
        from aiohttp import web

        return web.json_response(
            {
                "status": "ok",
                "deployments": {
                    name: len(rs.replicas)
                    for name, rs in self._apps.items()
                },
            }
        )

    async def _routes(self, request):
        from aiohttp import web

        return web.json_response(sorted(self._apps))

    # -- lifecycle ------------------------------------------------------
    def _run(self) -> None:
        try:
            from aiohttp import web
        except BaseException as exc:  # noqa: BLE001 - surfaced to __init__
            self._startup_error = exc
            self._started.set()
            return

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_get("/-/healthz", self._healthz)
        app.router.add_get("/-/routes", self._routes)
        app.router.add_post("/{deployment}/stream", self._stream)
        app.router.add_post("/{deployment}", self._call)

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", self._port)
            await site.start()
            self._runner = runner
            self.port = site._server.sockets[0].getsockname()[1]
            self._started.set()

        try:
            loop.run_until_complete(start())
        except BaseException as exc:  # noqa: BLE001 - bind failure etc.
            self._startup_error = exc
            self._started.set()
            return
        loop.run_forever()

    def shutdown(self) -> None:
        loop = self._loop
        if loop is None:
            return

        async def stop():
            if self._runner is not None:
                await self._runner.cleanup()
            loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(stop(), loop)
            self._thread.join(timeout=5)
        except RuntimeError:
            pass
        self._pool.shutdown(wait=False)
