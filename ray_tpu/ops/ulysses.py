"""Ulysses attention: all-to-all sequence parallelism over the ``sp`` axis.

Net-new relative to the reference (SURVEY §2.3 / §5: no SP/CP exists
there — sequence length is delegated to the wrapped engines). This is the
DeepSpeed-Ulysses scheme re-expressed as XLA collectives: with sequences
sharded over ``sp``, an ``all_to_all`` swaps the shard dimension from
sequence to heads, every device computes *full-sequence* attention for its
head slice (MXU-friendly single big matmul — no per-block online softmax),
and a second ``all_to_all`` swaps back. Two collectives per layer versus
ring attention's sp ppermutes; the better choice when heads ≥ sp and the
sequence fits per-device HBM once.

Use inside shard_map with sequence sharded over ``axis_name``:
    q: [B, T_local, H, D], k/v: [B, T_local, Hkv, D] per device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T_local, H, D] -> [B, T_full, H/sp, D] via tiled all-to-all."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T_full, H/sp, D] -> [B, T_local, H, D] (inverse swap)."""
    return jax.lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
) -> jax.Array:
    sp = jax.lax.axis_size(axis_name)
    b, t, h, d = q.shape
    hkv = k.shape[2]
    if h % sp != 0:
        raise ValueError(f"n_heads={h} must divide by sp={sp} for Ulysses")
    # GQA with fewer KV heads than sp: replicate KV heads up to sp so the
    # head all-to-all has something to split (grouping is preserved below).
    if hkv % sp != 0:
        import math

        reps = math.lcm(hkv, sp) // hkv  # smallest expansion divisible by sp
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
        hkv = hkv * reps
    qg = _heads_to_seq(q, axis_name)  # [B, T, h/sp, D]
    kg = _heads_to_seq(k, axis_name)  # [B, T, hkv/sp, D]
    vg = _heads_to_seq(v, axis_name)
    groups = qg.shape[2] // kg.shape[2]
    t_full = qg.shape[1]
    qh = qg.reshape(b, t_full, kg.shape[2], groups, d)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    s = (
        jnp.einsum(
            "bthgd,bshd->bhgts",
            qh.astype(jnp.float32),
            kg.astype(jnp.float32),
        )
        * scale
    )
    if causal:
        pos = jnp.arange(t_full)
        mask = pos[None, :] <= pos[:, None]  # [t, s]
        s = jnp.where(mask[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgts,bshd->bthgd", p, vg.astype(jnp.float32))
    o = o.reshape(b, t_full, qg.shape[2], d).astype(q.dtype)
    return _seq_to_heads(o, axis_name)  # back to [B, T_local, H, D]
