"""Ring attention: sequence/context parallelism over the mesh ``sp`` axis.

Net-new relative to the reference (SURVEY §2.3: no SP/CP exists there — it
only rents bigger vLLM TP configs). This is blockwise attention with an
online-softmax accumulator where each device holds a sequence shard and the
K/V shards rotate around the ring via ``jax.lax.ppermute`` — ICI traffic
overlaps with the local block matmuls under XLA async collectives.

Use inside shard_map with sequence sharded over ``axis_name``:
    q, k, v: [B, T_local, H, D] per device.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


from ray_tpu.ops._vma import match_vma as _match_vma


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
) -> jax.Array:
    sp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t, h, d = q.shape
    hkv = k.shape[2]
    groups = h // hkv
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qh = q.reshape(b, t, hkv, groups, d)

    q_pos = idx * t + jnp.arange(t)  # global positions of local queries

    def block(carry, step):
        o, m, l, k_cur, v_cur = carry
        kv_idx = (idx - step) % sp
        k_pos = kv_idx * t + jnp.arange(t)
        s = (
            jnp.einsum(
                "bthgd,bshd->bhgts",
                qh.astype(jnp.float32),
                k_cur.astype(jnp.float32),
            )
            * scale
        )
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
            s = jnp.where(mask[None, None, None], s, -1e30)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows: exp(-1e30 - m) underflows to 0 safely
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhgts,bshd->bthgd", p, v_cur.astype(jnp.float32))
        o_new = o * alpha.transpose(0, 3, 1, 2)[..., None] + pv
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    # Initial accumulators must carry the same varying-manual-axes type as
    # the q/k/v inputs (which may be varying over pp too, inside a pipeline
    # stage) so the scan carry type stays consistent.
    o0 = _match_vma(jnp.zeros((b, t, hkv, groups, d), jnp.float32), q)
    m0 = _match_vma(jnp.full((b, hkv, groups, t), -jnp.inf, jnp.float32), q)
    l0 = _match_vma(jnp.zeros((b, hkv, groups, t), jnp.float32), q)
    (o, m, l, _, _), _ = jax.lax.scan(
        block, (o0, m0, l0, k, v), jnp.arange(sp)
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = (o / denom).reshape(b, t, h, d)
    return out.astype(q.dtype)
