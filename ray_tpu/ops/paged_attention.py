"""Paged-attention decode as a Pallas TPU kernel.

The decode-step attention of the continuous-batching engine
(ray_tpu/llm/continuous.py): each slot's single query token attends over
its paged KV cache via a block table. The XLA formulation gathers every
slot's pages into a contiguous [S_max] view (one big materialized gather
per layer); this kernel instead walks the block table INSIDE the kernel —
pages stream out of the per-head pool and scores/weights never leave
VMEM, with an online-softmax accumulator across pages (the
JetStream/PagedAttention structure).

Grid: (batch_slot, kv_head). Per program: q [G, D] resident; fori_loop
over the slot's table entries; each iteration dynamically indexes one
[page, D] K/V tile from the head's pool slice and folds it into the
running max/sum/output.

VMEM note: the BlockSpec stages one HEAD's pool slice
(n_pages·page·head_dim elements) per program — with the engine defaults
(256 pages × 16 × 64 × bf16 ≈ 512 KB) this fits VMEM comfortably. Pools
larger than VMEM need the HBM-resident variant with explicit page DMA
(make_async_copy); the call signature is layout-compatible.

Numerics are validated against the XLA reference in interpret mode
(tests/test_paged_attention.py) and slot-for-slot against the engine's
gather path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _paged_kernel(
    tbl_ref,  # [B, P_max] int32 in SMEM — all block tables (scalar loads)
    len_ref,  # [B] int32 in SMEM — valid positions (q_pos + 1) per slot
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, N, page, D] — this kv head's pool slice
    v_ref,  # [1, N, page, D]
    o_ref,  # [1, 1, G, D]
    *,
    page: int,
    p_max: int,
    scale: float,
):
    g, d = q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0, 0] * scale  # [G, D]
    slot = pl.program_id(0)
    length = len_ref[slot]

    m0 = jnp.full((g,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((g,), jnp.float32)
    o0 = jnp.zeros((g, d), jnp.float32)

    def body(j, carry):
        m, l, o = carry
        pid = tbl_ref[slot, j]
        k_pg = k_ref[0, pid]  # [page, D] — dynamic page index into the pool
        v_pg = v_ref[0, pid]
        scores = jnp.dot(
            q, k_pg.T, preferred_element_type=jnp.float32
        )  # [G, page]
        pos = j * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
        scores = jnp.where(pos < length, scores, -1e30)
        m_blk = jnp.max(scores, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v_pg.dtype), v_pg, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    # only pages that hold valid positions contribute; masked pages beyond
    # the sequence are skipped entirely (live = ceil(length / page))
    live = jnp.minimum(p_max, (length + page - 1) // page)
    m, l, o = jax.lax.fori_loop(0, live, body, (m0, l0, o0))
    o_ref[0, 0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_attention_decode(
    q: jax.Array,  # [B, KH, G, D] one query token per slot, grouped heads
    k_pages: jax.Array,  # [KH, N_pages, page, D] head-major pool
    v_pages: jax.Array,  # [KH, N_pages, page, D]
    block_tables: jax.Array,  # [B, P_max] int32
    lengths: jax.Array,  # [B] int32 valid positions per slot
    *,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:  # [B, KH, G, D]
    b, kh, g, d = q.shape
    p_max = block_tables.shape[1]
    scale = 1.0 / (d**0.5)
    kernel = functools.partial(
        _paged_kernel, page=page_size, p_max=p_max, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=(b, kh),
        in_specs=[
            # block table + lengths are scalar control data: whole arrays
            # in SMEM (the Mosaic lowering rejects (1, P) VMEM windows on
            # int32 tables, and page ids drive addresses, not vectors)
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, g, d), lambda i, h: (i, h, 0, 0)),
            pl.BlockSpec(
                (1, k_pages.shape[1], page_size, d), lambda i, h: (h, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, v_pages.shape[1], page_size, d), lambda i, h: (h, 0, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, 1, g, d), lambda i, h: (i, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), q.dtype),
        interpret=interpret,
    )(block_tables, lengths, q, k_pages, v_pages)


def paged_attention_reference(
    q, k_pages, v_pages, block_tables, lengths, *, page_size
):
    """XLA gather formulation (the engine's default path) — the golden
    model the kernel is tested against."""
    b, kh, g, d = q.shape
    p_max = block_tables.shape[1]
    s_max = p_max * page_size
    # [B, P, page, KH→, D] per-slot gather, head-major pool in
    ks = jnp.transpose(k_pages, (1, 2, 0, 3))[  # [N, page, KH, D]
        block_tables
    ].reshape(b, s_max, kh, d)
    vs = jnp.transpose(v_pages, (1, 2, 0, 3))[block_tables].reshape(
        b, s_max, kh, d
    )
    scores = jnp.einsum(
        "bhgd,bshd->bhgs", q.astype(jnp.float32), ks.astype(jnp.float32)
    ) / (d**0.5)
    valid = jnp.arange(s_max)[None, :] < lengths[:, None]
    scores = jnp.where(valid[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum(
        "bhgs,bshd->bhgd", probs, vs.astype(jnp.float32)
    ).astype(q.dtype)
