"""Varying-manual-axes (vma) typing helpers for partial-manual shard_map."""
from __future__ import annotations

import jax


def match_vma(x: jax.Array, ref: jax.Array) -> jax.Array:
    """Promote x's varying-manual-axes set to include ref's."""
    missing = tuple(jax.typeof(ref).vma - jax.typeof(x).vma)
    return jax.lax.pcast(x, missing, to="varying") if missing else x
