"""Elementary model ops: RMSNorm, RoPE, SwiGLU, attention (jnp reference).

Pure-functional building blocks, written for XLA fusion: everything is
jnp-level so the compiler fuses the elementwise chains into the surrounding
matmuls (HBM-bandwidth discipline); the Pallas flash-attention kernel in
``flash_attention.py`` replaces ``attention_reference`` on TPU for long
sequences.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(dtype) * weight


def rope_freqs(head_dim: int, max_len: int, theta: float = 10000.0) -> jax.Array:
    """[max_len, head_dim//2] complex rotation angles."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(t, inv)  # [T, D/2]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """x: [..., T, H, D]; angles: [T, D/2] (already offset for this shard)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array):
    g = jax.nn.silu(x @ w_gate)
    return (g * (x @ w_up)) @ w_down


def attention_reference(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    q_offset: int = 0,
) -> jax.Array:
    """Plain softmax attention with GQA head-group broadcast. Numerics
    reference for the flash/ring kernels."""
    b, t, h, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    groups = h // hkv
    qh = q.reshape(b, t, hkv, groups, d)
    scores = jnp.einsum("bthgd,bshd->bhgts", qh, k) / jnp.sqrt(d).astype(q.dtype)
    scores = scores.astype(jnp.float32)
    if causal:
        q_pos = jnp.arange(t) + q_offset
        k_pos = jnp.arange(s)
        mask = k_pos[None, :] <= q_pos[:, None]
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgts,bshd->bthgd", p, v)
    return out.reshape(b, t, h, d)
