"""Flash attention as a Pallas TPU kernel.

Blockwise attention with an online-softmax accumulator: Q stays resident in
VMEM per grid step while K/V blocks stream HBM→VMEM; scores never
materialize in HBM (the memory win), and the causal grid skips fully-masked
K blocks (the compute win). Grid: (batch·kv_heads·groups, q_blocks).

Single-chip counterpart of ops/ring_attention.py (which handles the
sequence-sharded case over ICI); together they are the long-context story
the reference lacks natively (SURVEY §2.3).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .layers import attention_reference

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, S, d]; o_ref: [1, block_q, d]
    _, block_q, d = q_ref.shape
    s = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0] * scale

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        scores = jnp.dot(
            q, k_blk.T, preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            scores = jnp.where(k_pos <= q_pos, scores, -1e30)
        m_blk = jnp.max(scores, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    num_kb = s // block_k
    if causal:
        # K blocks strictly above this Q block's diagonal are fully masked.
        num_kb_live = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k + 1
        )
    else:
        num_kb_live = num_kb
    m, l, o = jax.lax.fori_loop(0, num_kb_live, body, (m0, l0, o0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    b, t, h, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    groups = h // hkv
    if t % block_q or s % block_k:
        # ragged tails fall back to the fused-XLA reference path
        return attention_reference(q, k, v, causal=causal)
    scale = 1.0 / (d**0.5)

    # layout: fold (batch, kv_head, group) into the grid's first axis; GQA
    # shares each K/V head across `groups` Q heads.
    qg = (
        q.reshape(b, t, hkv, groups, d)
        .transpose(0, 2, 3, 1, 4)
        .reshape(b * hkv * groups, t, d)
    )
    kg = (
        k.transpose(0, 2, 1, 3)[:, :, None]
        .repeat(groups, 2)
        .reshape(b * hkv * groups, s, d)
    )
    vg = (
        v.transpose(0, 2, 1, 3)[:, :, None]
        .repeat(groups, 2)
        .reshape(b * hkv * groups, s, d)
    )

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=(qg.shape[0], t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qg.shape, q.dtype),
        interpret=interpret,
    )(qg, kg, vg)
    return (
        out.reshape(b, hkv, groups, t, d)
        .transpose(0, 3, 1, 2, 4)
        .reshape(b, t, h, d)
    )
