"""Flash attention as Pallas TPU kernels — forward AND backward.

Blockwise attention with an online-softmax accumulator: Q stays resident in
VMEM per grid step while K/V blocks stream HBM→VMEM; scores never
materialize in HBM (the memory win), and the causal grid skips fully-masked
K blocks (the compute win). Grid: (batch·kv_heads·groups, q_blocks).

The backward pass is the FlashAttention-2 recipe: the forward saves only
the per-row logsumexp L; the backward recomputes score blocks on the fly
and accumulates dQ (grid over Q blocks) and dK/dV (grid over K blocks)
without ever materializing the [T, S] probability matrix. This is what
makes the flagship model's training step runnable on the TPU — without a
custom VJP, autodiff cannot see through pallas_call.

Single-chip counterpart of ops/ring_attention.py (which handles the
sequence-sharded case over ICI); together they are the long-context story
the reference lacks natively (SURVEY §2.3).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .layers import attention_reference

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, block_k: int,
                causal: bool, scale: float):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, S, d]; o_ref: [1, block_q, d]
    # l_ref: [1, 1, block_q] — per-row logsumexp saved for the backward
    # pass. lse/delta ride as [bh, 1, t] (not [bh, t]) so their block
    # specs' trailing dims are (1, block) with 1 == the full array dim —
    # the Mosaic TPU lowering rejects a (1, block) window on a 2-D array
    # whose sublane dim is larger.
    _, block_q, d = q_ref.shape
    s = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0] * scale

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(kb, carry):
        m, l, o = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        scores = jnp.dot(
            q, k_blk.T, preferred_element_type=jnp.float32
        )  # [bq, bk]
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            scores = jnp.where(k_pos <= q_pos, scores, -1e30)
        m_blk = jnp.max(scores, axis=1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1)
        o_new = o * alpha[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    num_kb = s // block_k
    if causal:
        # K blocks strictly above this Q block's diagonal are fully masked.
        num_kb_live = jnp.minimum(
            num_kb, (qi + 1) * block_q // block_k + 1
        )
    else:
        num_kb_live = num_kb
    m, l, o = jax.lax.fori_loop(0, num_kb_live, body, (m0, l0, o0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    l_ref[0, 0] = m + jnp.log(l_safe)  # logsumexp per row


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float):
    # per program: one Q block against all K blocks (same live set as fwd)
    _, block_q, d = q_ref.shape
    s = k_ref.shape[1]
    qi = pl.program_id(1)
    q = q_ref[0] * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        scores = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            scores = jnp.where(k_pos <= q_pos, scores, -1e30)
        p = jnp.exp(scores - lse[:, None])  # masked entries underflow to 0
        dp = jnp.dot(do, v_blk.T.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(
            ds.astype(k_blk.dtype), k_blk,
            preferred_element_type=jnp.float32,
        )

    num_kb = s // block_k
    if causal:
        num_kb_live = jnp.minimum(num_kb, (qi + 1) * block_q // block_k + 1)
    else:
        num_kb_live = num_kb
    dq = jax.lax.fori_loop(
        0, num_kb_live, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, causal: bool, scale: float):
    # per program: one K block against the Q blocks that can see it
    _, block_k, d = k_ref.shape
    t = q_ref.shape[1]
    ki = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :] * scale
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(
            jnp.float32
        )
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        scores = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0
            )
            scores = jnp.where(k_pos <= q_pos, scores, -1e30)
        p = jnp.exp(scores - lse_blk[:, None])  # [bq, bk]
        dv = dv + jnp.dot(
            p.T.astype(do_blk.dtype), do_blk,
            preferred_element_type=jnp.float32,
        )
        dp = jnp.dot(do_blk, v.T.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None])
        dk = dk + jnp.dot(
            ds.T.astype(q_blk.dtype), q_blk,
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    num_qb = t // block_q
    if causal:
        qb_start = ki * block_k // block_q  # earlier Q blocks see nothing
    else:
        qb_start = 0
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, num_qb, body, (dk0, dv0))
    # q_blk carried the scale into ds already — no second factor here
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _fwd_impl(qg, kg, vg, causal, block_q, block_k, interpret):
    bh, t, d = qg.shape
    s = kg.shape[1]
    scale = 1.0 / (d**0.5)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, qi: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi: (b, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qg.shape, qg.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(qg, kg, vg)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_grouped(qg, kg, vg, causal, block_q, block_k, interpret):
    """Grouped layout [B·KH·G, T, D]; K/V already repeated per group (the
    repeat sits OUTSIDE this boundary so autodiff sums dk/dv over groups)."""
    out, _ = _fwd_impl(qg, kg, vg, causal, block_q, block_k, interpret)
    return out


def _flash_grouped_fwd(qg, kg, vg, causal, block_q, block_k, interpret):
    out, lse = _fwd_impl(qg, kg, vg, causal, block_q, block_k, interpret)
    return out, (qg, kg, vg, out, lse)


def _flash_grouped_bwd(causal, block_q, block_k, interpret, res, do):
    qg, kg, vg, out, lse = res
    bh, t, d = qg.shape
    s = kg.shape[1]
    scale = 1.0 / (d**0.5)
    # delta_i = rowsum(dO ⊙ O): the softmax-jacobian correction term
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]  # [bh, 1, t] — same layout as lse (see _fwd_kernel)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_k=block_k, causal=causal, scale=scale
        ),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi: (b, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda b, qi: (b, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct(qg.shape, qg.dtype),
        interpret=interpret,
    )(qg, kg, vg, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, causal=causal, scale=scale
        ),
        grid=(bh, s // block_k),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((1, t, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, ki: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kg.shape, kg.dtype),
            jax.ShapeDtypeStruct(vg.shape, vg.dtype),
        ],
        interpret=interpret,
    )(qg, kg, vg, do, lse, delta)
    return dq, dk, dv


_flash_grouped.defvjp(_flash_grouped_fwd, _flash_grouped_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,  # [B, S, Hkv, D]
    *,
    causal: bool = True,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool = False,
) -> jax.Array:
    b, t, h, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    groups = h // hkv
    if t % block_q or s % block_k:
        if causal and t == s:
            # Ragged causal self-attention: zero-pad to the block multiple
            # and slice the pad rows back off. Exact — padded keys sit at
            # positions >= t, strictly in every real query's masked future,
            # and the pad's transpose discards their cotangents. Keeps the
            # O(T) flash memory profile on ragged lengths (e.g. the T-1
            # next-token training slice), where the reference fallback
            # would materialize [T, S] per layer.
            m = block_q * block_k // math.gcd(block_q, block_k)
            pad = -t % m
            zq = ((0, 0), (0, pad), (0, 0), (0, 0))
            out = flash_attention(
                jnp.pad(q, zq), jnp.pad(k, zq), jnp.pad(v, zq),
                causal=True, block_q=block_q, block_k=block_k,
                interpret=interpret,
            )
            return out[:, :t]
        # ragged cross/non-causal tails fall back to the fused-XLA path
        return attention_reference(q, k, v, causal=causal)

    # layout: fold (batch, kv_head, group) into the grid's first axis; GQA
    # shares each K/V head across `groups` Q heads. The repeat stays
    # outside the custom-vjp boundary so dk/dv sum over groups for free.
    qg = (
        q.reshape(b, t, hkv, groups, d)
        .transpose(0, 2, 3, 1, 4)
        .reshape(b * hkv * groups, t, d)
    )
    kg = (
        k.transpose(0, 2, 1, 3)[:, :, None]
        .repeat(groups, 2)
        .reshape(b * hkv * groups, s, d)
    )
    vg = (
        v.transpose(0, 2, 1, 3)[:, :, None]
        .repeat(groups, 2)
        .reshape(b * hkv * groups, s, d)
    )

    out = _flash_grouped(qg, kg, vg, causal, block_q, block_k, interpret)
    return (
        out.reshape(b, hkv, groups, t, d)
        .transpose(0, 3, 1, 2, 4)
        .reshape(b, t, h, d)
    )
