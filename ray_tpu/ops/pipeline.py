"""Pipeline parallelism: GPipe-style microbatch schedule over ``ppermute``.

The reference only forwards ``pipeline_parallel_size`` to vLLM (SURVEY §2.3);
here PP is native: layer stages live on different devices along the mesh
``pp`` axis and activations hop stage→stage over ICI/DCN with
``jax.lax.ppermute`` inside shard_map. ``ppermute`` is differentiable, so the
same schedule runs under ``jax.grad`` (backward traffic flows the reverse
ring automatically).

Schedule: plain GPipe fill-drain — M microbatches over S stages completes in
M + S - 1 ticks. Bubble fraction (S-1)/(M+S-1); callers pick M >= 4*S.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def pipeline_apply(
    stage_fn: Callable,   # (stage_params, x[mb, ...]) -> y[mb, ...]
    stage_params,         # this device's stage parameters (inside shard_map)
    x: jax.Array,         # [M, mb, ...] all microbatches (replicated over pp)
    axis_name: str = "pp",
) -> jax.Array:
    """Run x through all pipeline stages; returns [M, mb, ...] outputs valid
    on every device (broadcast from the last stage via psum)."""
    from ray_tpu.ops._vma import match_vma

    pp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    # Microbatches may arrive replicated over pp; the per-stage compute is
    # pp-varying (each stage holds different layers), so promote up front.
    if axis_name not in jax.typeof(x).vma:
        x = jax.lax.pcast(x, axis_name, to="varying")
    m = x.shape[0]
    is_first = idx == 0
    is_last = idx == pp - 1
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 injects microbatch t (while t < M); others take the handoff.
        mb_idx = jnp.minimum(t, m - 1)
        inject = jax.lax.dynamic_index_in_dim(x, mb_idx, keepdims=False)
        inp = jnp.where(is_first, inject, recv)
        out = stage_fn(stage_params, inp)
        # Last stage banks its finished microbatch (valid when t >= pp-1).
        done_idx = jnp.clip(t - (pp - 1), 0, m - 1)
        banked = jax.lax.dynamic_update_index_in_dim(
            outputs, out, done_idx, axis=0
        )
        outputs = jnp.where(is_last & (t >= pp - 1), banked, outputs)
        recv = jax.lax.ppermute(out, axis_name, fwd_perm)
        return (recv, outputs), None

    recv0 = jnp.zeros_like(stage_fn(stage_params, x[0]))  # inherits pp-varying
    out0 = match_vma(jnp.zeros((m,) + recv0.shape, recv0.dtype), recv0)
    (_, outputs), _ = jax.lax.scan(
        tick, (recv0, out0), jnp.arange(m + pp - 1)
    )
    # Broadcast the last stage's outputs to all pp ranks so downstream
    # (head/loss) code is SPMD-uniform.
    outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis_name)
