"""Compute ops: layers, ring attention, pipeline schedule, pallas kernels."""
from .layers import (  # noqa: F401
    apply_rope,
    attention_reference,
    rms_norm,
    rope_freqs,
    swiglu,
)
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .pipeline import pipeline_apply  # noqa: F401
