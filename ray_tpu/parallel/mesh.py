"""Device-mesh management: the TPU-native replacement for process groups.

The reference builds parallelism from worker actors + NCCL groups
(/root/reference/python/ray/util/collective/collective.py,
train/torch/config.py:44). On TPU the equivalent is a named
``jax.sharding.Mesh`` over the chips with XLA collectives riding ICI; this
module owns mesh construction and the canonical axis names used by every
model/op in the framework:

- ``dp`` — data parallel (batch)
- ``pp`` — pipeline parallel (layer stages over ppermute)
- ``tp`` — tensor parallel (heads / hidden, Megatron-style)
- ``sp`` — sequence/context parallel (ring attention over ppermute)
- ``ep`` — expert parallel (MoE experts; aliases the dp axis devices)
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "tp", "sp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp * self.sp

    @staticmethod
    def auto(n_devices: int) -> "MeshConfig":
        """Factor n into a balanced (dp, pp, tp) mesh, largest factors to dp.

        Heuristic for dry-runs/tests; production configs are explicit.
        """
        factors = _prime_factors(n_devices)
        dims = [1, 1, 1]  # dp, pp, tp
        for f in sorted(factors, reverse=True):
            i = dims.index(min(dims))
            dims[i] *= f
        dp, pp, tp = sorted(dims, reverse=True)
        return MeshConfig(dp=dp, pp=pp, tp=tp, sp=1)


def _prime_factors(n: int) -> list:
    out, d = [], 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if len(devices) < config.size:
        raise ValueError(
            f"mesh needs {config.size} devices, have {len(devices)}"
        )
    arr = np.array(devices[: config.size]).reshape(
        config.dp, config.pp, config.tp, config.sp
    )
    return Mesh(arr, AXES)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def local_mesh() -> Mesh:
    """Single-device mesh (all axes size 1) — the degenerate config every
    model must also run under (single-chip entry point)."""
    return build_mesh(MeshConfig(), jax.devices()[:1])
