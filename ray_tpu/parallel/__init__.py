"""Parallelism: mesh construction, axis conventions, sharding helpers."""
from .mesh import (  # noqa: F401
    AXES,
    MeshConfig,
    build_mesh,
    local_mesh,
    sharding,
)
