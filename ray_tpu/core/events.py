"""Task lifecycle events + chrome-trace timeline export.

Analog of the reference's TaskEventBuffer → GcsTaskManager pipeline
(src/ray/core_worker/task_event_buffer.h:304) and ray.timeline()
(python/ray/_private/state.py:1010): every task transition is recorded in a
bounded ring buffer; ``dump_timeline`` renders Chrome tracing JSON.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class TaskEvent:
    task_id: str
    name: str
    state: str  # SUBMITTED | SCHEDULED | RUNNING | FINISHED | FAILED
    timestamp: float
    node_id: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)


class TaskEventBuffer:
    def __init__(self, max_events: int = 100_000):
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def record(self, task_id: str, name: str, state: str,
               node_id: str = "", **extra) -> None:
        with self._lock:
            self._events.append(
                TaskEvent(task_id, name, state, time.time(), node_id, extra)
            )

    def events(self, task_id: Optional[str] = None) -> List[TaskEvent]:
        with self._lock:
            evs = list(self._events)
        if task_id is not None:
            evs = [e for e in evs if e.task_id == task_id]
        return evs

    def task_states(self) -> Dict[str, TaskEvent]:
        """Latest event per task."""
        out: Dict[str, TaskEvent] = {}
        for e in self.events():
            out[e.task_id] = e
        return out

    def dump_timeline(
        self, path: Optional[str] = None, include_process_spans: bool = True
    ) -> List[dict]:
        """Chrome tracing format: one complete ('X') slice per RUNNING →
        FINISHED/FAILED pair, plus instant events for queueing states.

        Instant events carry their recorded ``extra`` (trace ids, and —
        for SCHEDULED events the head stamps — the scheduler's per-term
        cost breakdown), so one trace answers both "where did it run"
        and "why was it placed there". Process-level spans from
        ``util.tracing.SPANS`` (scheduler rounds, serve requests, socket
        stripes, elastic reshape phases) merge into the same export."""
        spans: List[dict] = []
        open_running: Dict[str, TaskEvent] = {}
        for e in self.events():
            if e.state == "RUNNING":
                open_running[e.task_id] = e
            elif e.state in ("FINISHED", "FAILED") and e.task_id in open_running:
                start = open_running.pop(e.task_id)
                trace_args = {
                    k: v
                    for src in (start.extra, e.extra)
                    for k, v in src.items()
                    if k in ("trace_id", "parent_id")
                }
                spans.append(
                    {
                        "name": e.name,
                        "cat": "task",
                        "ph": "X",
                        "ts": start.timestamp * 1e6,
                        "dur": (e.timestamp - start.timestamp) * 1e6,
                        "pid": start.node_id or "cluster",
                        "tid": e.extra.get("worker", 0),
                        "args": {
                            "state": e.state,
                            "task_id": e.task_id,
                            **trace_args,
                        },
                    }
                )
            elif e.state in ("SUBMITTED", "SCHEDULED"):
                span = {
                    "name": f"{e.name}:{e.state.lower()}",
                    "cat": "scheduler",
                    "ph": "i",
                    "s": "p",
                    "ts": e.timestamp * 1e6,
                    "pid": e.node_id or "cluster",
                    "tid": 0,
                }
                if e.extra:
                    span["args"] = {"task_id": e.task_id, **e.extra}
                spans.append(span)
        if include_process_spans:
            try:
                from ray_tpu.util.tracing import SPANS

                spans.extend(SPANS.slices())
            except Exception:  # noqa: BLE001 - export must not fail
                pass
        if path:
            with open(path, "w") as f:
                json.dump(spans, f)
        return spans
