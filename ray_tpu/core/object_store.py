"""In-memory object plane: ObjectRef + owner-tracked store.

Single-process analog of the reference's object plane (plasma +
CoreWorkerMemoryStore, /root/reference/src/ray/core_worker/store_provider/):
objects are immutable once sealed; readers block until sealed; task errors
are stored as first-class values and re-raised on get (RayTaskError
semantics, python/ray/exceptions.py). Ownership/refcounting is tracked per
object so lineage-based recovery can be layered on (reference_counter.h:44).
"""
from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import refcount


class TaskError(Exception):
    """Wraps an exception raised in a remote task (RayTaskError analog);
    carries the remote traceback text like the reference
    (python/ray/exceptions.py RayTaskError.__str__)."""

    def __init__(
        self,
        cause: BaseException,
        task_desc: str = "",
        traceback_str: str = "",
    ):
        msg = f"task {task_desc} failed: {cause!r}"
        if traceback_str:
            msg += f"\n\nremote traceback:\n{traceback_str}"
        super().__init__(msg)
        self.cause = cause
        self.task_desc = task_desc
        self.traceback_str = traceback_str


class ObjectLostError(Exception):
    pass


class OwnerDiedError(Exception):
    """The process that owned this object (submitted its creating task or
    held its only record) died before the object could be produced.
    Objects fate-share with their owner — the reference's OwnerDiedError
    (python/ray/exceptions.py): dependents raise this typed error instead
    of hanging on an object that will never seal."""


class GetTimeoutError(TimeoutError):
    pass


class _RefWaiter:
    """One daemon thread multiplexing every pending .future()/__await__
    resolution. A thread-per-ref (or bounded-pool) design head-of-line
    blocks: N concurrently awaited unresolved refs starve every later
    await, including refs whose objects are already sealed (the reference
    resolves event-driven via _to_future, object_ref.pxi). Here the single
    waiter asks the runtime's wait primitive for ANY ready ref, resolves
    those (get_object returns promptly once sealed), and completes their
    futures — unresolved refs cost a slot in a dict, not a thread."""

    _MAX_RESOLVERS = 4

    def __init__(self) -> None:
        self._cv = threading.Condition()
        # hex -> (ref, [futures]); many futures may await one ref
        self._pending: Dict[str, tuple] = {}
        self._generation = 0  # bumped per submit: shrinks the poll window
        # READY refs resolve on up to _MAX_RESOLVERS DAEMON threads: one
        # slow large cross-node fetch must not head-of-line block
        # completion of every other already-sealed awaited ref (r4
        # advisor); only the wait_many multiplexing stays on the single
        # thread. Plain daemon threads, not a ThreadPoolExecutor — its
        # atexit join would hold interpreter shutdown for a fetch in
        # flight (up to the 5s get timeout).
        self._resolving: set = set()  # hexes being fetched right now
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ref-await"
        )
        self._thread.start()

    def submit(self, ref: "ObjectRef"):
        from concurrent.futures import Future

        fut: Future = Future()
        with self._cv:
            self._pending.setdefault(ref.hex, (ref, []))[1].append(fut)
            self._generation += 1
            self._cv.notify()
        return fut

    def _loop(self) -> None:
        import time

        window = 0.2
        last_gen = -1
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                # refs mid-fetch in the resolve pool stay OUT of the wait
                # set: wait_many reports a sealed ref ready instantly, so
                # including one being slow-fetched turns this loop into a
                # zero-delay spin (head RPC storm in cluster mode)
                refs = [
                    r
                    for h, (r, _) in self._pending.items()
                    if h not in self._resolving
                ]
                gen = self._generation
            if not refs:
                time.sleep(0.05)  # everything pending is mid-fetch
                continue
            # adaptive window: freshly submitted refs get a short wait (a
            # just-sealed object resolves fast); an unchanged pending set
            # backs the window off so one long-running awaited task does
            # not turn into a 5 Hz head poll in cluster mode
            window = 0.2 if gen != last_gen else min(2.0, window * 2)
            last_gen = gen
            rt = None
            try:
                from ray_tpu.core.runtime import get_runtime

                rt = get_runtime()
                ready, _ = rt.store.wait_many(refs, 1, window)
            except Exception:  # noqa: BLE001 - runtime mid-swap/teardown
                ready = []
                time.sleep(0.05)
            slots_full = False
            for r in ready:
                with self._cv:
                    if r.hex in self._resolving:
                        continue  # already owned by a resolver
                    if len(self._resolving) >= self._MAX_RESOLVERS:
                        slots_full = True
                        continue
                    self._resolving.add(r.hex)
                threading.Thread(
                    target=self._resolve_one,
                    args=(rt, r),
                    daemon=True,
                    name="ref-resolve",
                ).start()
            if slots_full:
                # a sealed ref is waiting on a slot: wait_many would
                # return it instantly, so pause instead of re-polling in
                # a zero-delay spin until a resolver frees up
                time.sleep(0.05)

    def _resolve_one(self, rt, r: "ObjectRef") -> None:
        try:
            try:
                value, is_err = rt.get_object(r, 5.0), False
            except GetTimeoutError:
                # sealed but the fetch is slow (large cross-node object):
                # leave it pending and retry next round rather than
                # surfacing a timeout the caller never asked for
                return
            except BaseException as exc:  # noqa: BLE001
                value, is_err = exc, True
            with self._cv:
                entry = self._pending.pop(r.hex, None)
            for fut in entry[1] if entry else ():
                try:
                    if is_err:
                        fut.set_exception(value)
                    else:
                        fut.set_result(value)
                except Exception:  # noqa: BLE001 - future cancelled
                    pass
        finally:
            with self._cv:
                self._resolving.discard(r.hex)


_RESOLVER = None
_RESOLVER_LOCK = threading.Lock()


def _resolver() -> _RefWaiter:
    global _RESOLVER
    with _RESOLVER_LOCK:
        if _RESOLVER is None:
            _RESOLVER = _RefWaiter()
        return _RESOLVER


def should_await(value) -> bool:
    """True for awaitables an executor should transparently await on a
    user function's behalf. ObjectRef is awaitable but EXEMPT: returning
    a ref hands the ref to the caller (reference semantics) — resolving
    it here would change the return shape and block the executor."""
    import inspect

    return inspect.isawaitable(value) and not isinstance(value, ObjectRef)


@dataclass(frozen=True)
class ObjectRef:
    """A future-like handle to a task output or put object.

    28-hex ids like the reference's ObjectID (src/ray/common/id.h). Every
    instance participates in distributed reference counting: construction
    (including unpickling) increfs the process tracker, ``__del__`` decrefs
    — the CPython-side hook the reference uses for RemoveLocalReference
    (python/ray/includes/object_ref.pxi). Internal bookkeeping that must
    not pin an object uses ``ObjectRef.weak``.
    """

    hex: str
    owner: str = ""  # owning "worker"/task id — lineage anchor

    def __post_init__(self) -> None:
        refcount.TRACKER.incref(self.hex)
        object.__setattr__(self, "_counted", True)
        refcount.note_deserialized(self.hex)

    def __del__(self) -> None:
        if getattr(self, "_counted", False):
            try:
                refcount.TRACKER.decref(self.hex)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass

    def __reduce__(self):
        refcount.note_serialized(self.hex)
        return (ObjectRef, (self.hex, self.owner))

    def __await__(self):
        """``await ref`` resolves the object without blocking the event
        loop (reference: awaitable ObjectRefs, object_ref.pxi _to_future —
        asyncio actors await refs inside methods). NOTE: executors that
        auto-await user return values must exempt ObjectRef — a method
        RETURNING a ref means "hand the ref over", not "resolve it"
        (see should_await)."""
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def future(self):
        """concurrent.futures.Future view of this ref (ray parity);
        resolved event-driven by the shared multiplexing waiter — any
        number of unresolved refs can be awaited concurrently without
        head-of-line blocking."""
        return _resolver().submit(self)

    @staticmethod
    def new(owner: str = "") -> "ObjectRef":
        # buffered urandom (ray_tpu._ids): collision-proof at 14 random
        # bytes with no syscall per id; this sits on the per-call hot
        # path of every task/actor submission
        from ray_tpu._ids import rand_hex

        return ObjectRef(rand_hex(14), owner)

    @staticmethod
    def weak(hex_id: str, owner: str = "") -> "ObjectRef":
        """An uncounted handle for runtime-internal plumbing (lineage
        clones, seal paths) that must not keep the object alive."""
        self = object.__new__(ObjectRef)
        object.__setattr__(self, "hex", hex_id)
        object.__setattr__(self, "owner", owner)
        object.__setattr__(self, "_counted", False)
        return self

    def __repr__(self) -> str:
        return f"ObjectRef({self.hex})"

    def __hash__(self) -> int:
        return hash(self.hex)


@dataclass
class _Entry:
    event: threading.Event = field(default_factory=threading.Event)
    value: Any = None
    is_error: bool = False
    creating_task: Optional[str] = None  # lineage: task id that creates this
    # user dropped every handle before the creating task sealed: free the
    # value the moment the seal lands instead of storing it
    unreferenced: bool = False


NATIVE_THRESHOLD_BYTES = 64 * 1024


class _NativeHandle:
    __slots__ = ("hex",)

    def __init__(self, hex_id: str):
        self.hex = hex_id


class ObjectStore:
    """Process-wide store; thread-safe.

    Large numpy arrays are spilled into the native shared-memory arena
    (ray_tpu.native, the plasma analog) and read back as zero-copy views;
    small/other objects stay in-process (the CoreWorkerMemoryStore split at
    max_direct_call_object_size, ray_config_def.h:218).
    """

    def __init__(self, native=None) -> None:
        self._lock = threading.Lock()
        self._objects: Dict[str, _Entry] = {}
        self._native = native

    def _maybe_nativize(self, hex_id: str, value: Any):
        import numpy as np

        if (
            self._native is not None
            and isinstance(value, np.ndarray)
            and value.nbytes >= NATIVE_THRESHOLD_BYTES
        ):
            try:
                self._native.put_numpy(hex_id, value)
                return _NativeHandle(hex_id)
            except (MemoryError, KeyError, OSError):
                return value
        return value

    def _denativize(self, value: Any) -> Any:
        if isinstance(value, _NativeHandle):
            return self._native.get_numpy(value.hex)
        return value

    def create(self, ref: ObjectRef, creating_task: Optional[str] = None) -> None:
        self.create_id(ref.hex, creating_task)

    def create_id(self, hex_id: str, creating_task: Optional[str] = None) -> None:
        with self._lock:
            if hex_id not in self._objects:
                self._objects[hex_id] = _Entry(creating_task=creating_task)

    def seal(self, ref: ObjectRef, value: Any, is_error: bool = False) -> bool:
        return self.seal_id(ref.hex, value, is_error)

    def seal_id(self, hex_id: str, value: Any, is_error: bool = False) -> bool:
        """Seal and return True if every handle was already dropped (the
        caller should free the object + its lineage immediately)."""
        if not is_error:
            value = self._maybe_nativize(hex_id, value)
        with self._lock:
            entry = self._objects.setdefault(hex_id, _Entry())
            entry.value = value
            entry.is_error = is_error
            entry.event.set()
            return entry.unreferenced

    def contains(self, ref: ObjectRef) -> bool:
        with self._lock:
            e = self._objects.get(ref.hex)
            return e is not None and e.event.is_set()

    def get(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        with self._lock:
            entry = self._objects.setdefault(ref.hex, _Entry())
        if not entry.event.wait(timeout):
            raise GetTimeoutError(f"get() timed out waiting for {ref}")
        if entry.is_error:
            if isinstance(entry.value, BaseException):
                raise entry.value
            raise TaskError(RuntimeError(str(entry.value)))
        return self._denativize(entry.value)

    def wait_many(
        self,
        refs: List[ObjectRef],
        num_returns: int,
        timeout: Optional[float],
    ) -> tuple[List[ObjectRef], List[ObjectRef]]:
        """ray.wait semantics: (ready, not_ready), preserving input order."""
        deadline = None if timeout is None else (timeout + _now())
        ready: List[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            progressed = False
            still: List[ObjectRef] = []
            for r in pending:
                if self.contains(r):
                    ready.append(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        still.extend(pending[pending.index(r) + 1 :])
                        break
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and _now() >= deadline:
                break
            if not progressed:
                remaining = None if deadline is None else max(0.0, deadline - _now())
                self._wait_any(pending, remaining)
        return ready, pending

    def _wait_any(self, refs: List[ObjectRef], timeout: Optional[float]) -> None:
        if not refs:
            return
        with self._lock:
            events = [self._objects.setdefault(r.hex, _Entry()).event for r in refs]
        step = 0.005
        waited = 0.0
        while True:
            for e in events:
                if e.is_set():
                    return
            if timeout is not None and waited >= timeout:
                return
            events[0].wait(step)
            waited += step
            step = min(step * 2, 0.1)

    def free(self, refs: List[ObjectRef]) -> None:
        for r in refs:
            self.free_id(r.hex)

    def free_id(self, hex_id: str) -> bool:
        """Drop a sealed entry (idempotent). An unsealed entry is flagged so
        the eventual seal frees it. Returns True if an entry was removed."""
        with self._lock:
            e = self._objects.get(hex_id)
            if e is None:
                return False
            if not e.event.is_set():
                e.unreferenced = True
                return False
            del self._objects[hex_id]
        if isinstance(e.value, _NativeHandle):
            try:
                self._native.delete(e.value.hex)
            except Exception:  # noqa: BLE001
                pass
        return True

    def stats(self) -> Dict[str, int]:
        with self._lock:
            sealed = sum(1 for e in self._objects.values() if e.event.is_set())
            return {"num_objects": len(self._objects), "num_sealed": sealed}


def _now() -> float:
    import time

    return time.monotonic()


class ObjectRefGenerator:
    """Iterator over the incrementally-produced returns of a
    ``num_returns="streaming"`` task (object_ref_generator.py /
    _raylet.pyx:246 analog).

    Each ``__next__`` blocks until the executor has sealed the next item,
    then returns its ``ObjectRef`` — normal object-plane semantics apply
    (``ray_tpu.get``, ``ray_tpu.wait``, passing to other tasks, GC on
    drop, recovery through the deterministic item ids on executor
    retry). A task exception surfaces as a final ref whose ``get()``
    raises (reference semantics); iteration then stops. The runtime is
    duck-typed: both the in-process runtime and the cluster client
    implement ``stream_next(task_id, index, timeout)``.
    """

    def __init__(self, task_id: str, runtime):
        self._task_id = task_id
        self._rt = runtime
        self._index = 0
        self._exhausted = False

    @property
    def task_id(self) -> str:
        return self._task_id

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> "ObjectRef":
        # iterator protocol: once exhausted, keep raising StopIteration —
        # the runtime drops the drained stream state on the None return,
        # so asking it again would block on a stream that no longer exists
        if self._exhausted:
            raise StopIteration
        ref = self._rt.stream_next(self._task_id, self._index, None)
        if ref is None:
            self._exhausted = True
            raise StopIteration
        self._index += 1
        return ref

    def next_ref(self, timeout: Optional[float] = None) -> "ObjectRef":
        """``__next__`` with a timeout (raises GetTimeoutError)."""
        if self._exhausted:
            raise StopIteration
        ref = self._rt.stream_next(self._task_id, self._index, timeout)
        if ref is None:
            self._exhausted = True
            raise StopIteration
        self._index += 1
        return ref

    def __del__(self):
        # consumer dropped the generator mid-stream: tell the runtime so
        # the executor's backpressure window opens (it would otherwise
        # wedge forever waiting for a watermark that can't move) and the
        # stream state becomes GC-eligible. Best-effort: interpreter
        # teardown may have already dismantled the runtime.
        if self._exhausted:
            return
        try:
            self._rt.stream_abandon(self._task_id)
        except Exception:  # noqa: BLE001
            pass

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ObjectRefGenerator({self._task_id}, at={self._index})"
