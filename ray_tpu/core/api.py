"""Public API: init/remote/get/put/wait — parity with the reference's
python surface (/root/reference/python/ray/_private/worker.py:1406,
remote_function.py:314, actor.py:1024)."""
from __future__ import annotations

import functools
import os
import uuid
from typing import Any, Dict, List, Optional, Sequence, Union

from .object_store import (  # noqa: F401  (re-exported errors)
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    OwnerDiedError,
    TaskError,
)
from .runtime import (
    ActorDiedError,  # noqa: F401
    NodeDiedError,  # noqa: F401
    Runtime,
    TaskSpec,
    get_context,
    get_runtime,
    runtime_initialized,
    set_runtime,
)
from . import actor as actor_mod


def init(
    num_nodes: int = 1,
    resources_per_node: Optional[Dict[str, float]] = None,
    *,
    address: Optional[str] = None,
    runtime_env: Optional[dict] = None,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    use_device_scheduler: Optional[bool] = None,
    ignore_reinit_error: bool = False,
):
    """Start the in-process cluster runtime, or connect to a live cluster.

    With ``address=None``: ``num_nodes`` simulated nodes in-process, each
    with ``resources_per_node`` — the single-process multi-node model
    (reference cluster_utils.Cluster, python/ray/cluster_utils.py:137).
    With ``address="host:port"``: connect this driver to a running
    multi-process cluster's head (the distributed runtime in
    ray_tpu.cluster; the reference's ray.init(address=...) +
    Ray-Client mode). The scheduler runs the batched XLA kernels on the
    device selected by ``RAY_TPU_SCHED_PLATFORM`` (default host XLA; set
    "tpu" to pin the chip) — ``use_device_scheduler=False`` or
    ``RAY_TPU_DEVICE_SCHEDULER=0`` selects the NumPy golden model instead.
    """
    if runtime_initialized():
        if ignore_reinit_error:
            return get_runtime()
        raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
    if address is None:
        from ray_tpu.config import cfg

        address = cfg.head_address or None
    if address is not None:
        from ray_tpu.cluster.client import RemoteRuntime

        remote_rt = RemoteRuntime(address, runtime_env=runtime_env)
        set_runtime(remote_rt)
        return remote_rt
    if resources_per_node is None:
        resources_per_node = {}
        if num_cpus is not None:
            resources_per_node["CPU"] = float(num_cpus)
        if num_tpus is not None:
            resources_per_node["TPU"] = float(num_tpus)
        if resources:
            resources_per_node.update(resources)
        if not resources_per_node:
            resources_per_node = {"CPU": 8.0, "memory": float(4 << 30)}
        resources_per_node.setdefault("CPU", 8.0)
        resources_per_node.setdefault("memory", float(4 << 30))
    rt = Runtime(
        num_nodes=num_nodes,
        resources_per_node=resources_per_node,
        use_device_scheduler=use_device_scheduler,
    )
    set_runtime(rt)
    return rt


def shutdown() -> None:
    if runtime_initialized():
        get_runtime().shutdown()
        set_runtime(None)


def is_initialized() -> bool:
    return runtime_initialized()


def put(value: Any) -> ObjectRef:
    return get_runtime().put_object(value)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    rt = get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get_object(refs, timeout)
    refs = list(refs)
    batched = getattr(rt, "get_objects", None)
    if batched is not None and len(refs) > 1:
        return batched(refs, timeout)
    return [rt.get_object(r, timeout) for r in refs]


def wait(
    refs: List[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> tuple:
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns={num_returns} exceeds the number of refs ({len(refs)})"
        )
    if num_returns < 1:
        raise ValueError("num_returns must be >= 1")
    rt = get_runtime()
    return rt.store.wait_many(refs, num_returns, timeout)


def kill(actor_handle, *, no_restart: bool = True) -> None:
    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        rt.kill_actor(actor_handle, no_restart=no_restart)
        return
    state = actor_handle._actor_state
    state.mark_died(restart=not no_restart)
    if state._held_req is not None:
        node_id, req, assign = state._held_req
        node = rt.nodes.get(node_id)
        if node is not None and node.alive:
            if req is not None:  # None for PG actors: the bundle held it
                node.ledger.release(req)
                rt.view.update_available(node_id, node.ledger.avail_map())
            if assign and node.accel:
                node.accel.release(assign)
        state._held_req = None
    rt.notify_resources_changed()


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    """Best-effort cancel: tasks still queued are dropped (running tasks in
    the thread-pool model cannot be preempted, like non-force cancel in the
    reference)."""
    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        rt.cancel_object(ref, force=force)
        return
    with rt._cond:
        for q in (rt._pending, rt._infeasible, rt._dep_waiting):
            for spec in list(q):
                if ref.hex in spec.return_ids:
                    q.remove(spec)
                    err = TaskError(RuntimeError("task cancelled"), spec.name)
                    for rid in spec.return_ids:  # seal every sibling return
                        rt._seal_id(None, rid, err, True)


class RuntimeContext:
    """Per-task/actor execution context (ray.get_runtime_context parity,
    python/ray/runtime_context.py). Accelerator ids come from the granted
    lease's chip assignment — in cluster workers via the exported
    TPU_VISIBLE_CHIPS / CUDA_VISIBLE_DEVICES env vars."""

    def __init__(self, node_id, task_id, actor_id, accelerator_ids):
        self.node_id = node_id
        self.task_id = task_id
        self.actor_id = actor_id
        self._accelerator_ids = accelerator_ids

    def get_node_id(self):
        return self.node_id

    def get_task_id(self):
        return self.task_id

    def get_actor_id(self):
        return self.actor_id

    def get_accelerator_ids(self) -> Dict[str, List[str]]:
        return {k: [str(i) for i in v] for k, v in self._accelerator_ids.items()}


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.scheduler.instances import ACCELERATOR_ENV_VARS

    ctx = get_context()
    accel = dict(getattr(ctx, "accelerator_ids", None) or {})
    if not accel:
        # cluster worker: assignment arrives as exported env vars
        for name, var in ACCELERATOR_ENV_VARS.items():
            val = os.environ.get(var)
            if val:
                accel[name] = val.split(",")
    return RuntimeContext(ctx.node_id, ctx.task_id, ctx.actor_id, accel)


def nodes() -> List[Dict[str, Any]]:
    return get_runtime().nodes_info()


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace dump of task lifecycle events (ray.timeline parity,
    reference _private/state.py:1010)."""
    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        return rt.timeline(filename)
    return rt.events.dump_timeline(filename)


def cluster_resources() -> Dict[str, float]:
    return get_runtime().cluster_resources()


def available_resources() -> Dict[str, float]:
    return get_runtime().available_resources()


def get_actor(name: str):
    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        return rt.get_actor(name)
    actor_id = rt._named_actors.get(name)
    if actor_id is None:
        raise ValueError(f"no actor named {name!r}")
    state = rt._actors[actor_id]
    return actor_mod.ActorHandle(rt, actor_id, state.cls)


def actor_exited(handle) -> bool:
    return handle._actor_state.dead_forever


# ---------------------------------------------------------------------------
# @remote
# ---------------------------------------------------------------------------


_OPTION_DEFAULTS = dict(
    num_cpus=None,
    num_gpus=None,
    num_tpus=None,
    memory=None,
    resources=None,
    num_returns=1,
    max_retries=3,
    retry_exceptions=False,
    scheduling_strategy=None,
    name=None,
    lifetime=None,
    max_restarts=0,
    max_task_retries=0,
    max_concurrency=None,
    concurrency_groups=None,
)


def _resource_map(opts: dict, is_actor: bool) -> Dict[str, float]:
    res: Dict[str, float] = {}
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    elif not is_actor:
        res["CPU"] = 1.0  # reference default: tasks need 1 CPU
    if opts.get("num_gpus") is not None:
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("num_tpus") is not None:
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("memory") is not None:
        res["memory"] = float(opts["memory"])
    for k, v in (opts.get("resources") or {}).items():
        res[k] = float(v)
    return res


class RemoteFunction:
    def __init__(self, fn, options: dict):
        self._fn = fn
        self._options = options
        functools.update_wrapper(self, fn)

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(overrides)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu._ids import rand_hex

        rt = get_runtime()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        ctx = get_context()
        owner = ctx.task_id or "driver"
        refs = (
            []
            if streaming
            else [ObjectRef.new(owner=owner) for _ in range(num_returns)]
        )
        spec = TaskSpec(
            task_id=rand_hex(8),
            func=self._fn,
            args=args,
            kwargs=kwargs,
            returns=refs,
            resources=_resource_map(opts, is_actor=False),
            name=opts.get("name") or self._fn.__name__,
            strategy=opts.get("scheduling_strategy"),
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            runtime_env=opts.get("runtime_env"),
            streaming=streaming,
        )
        rt.submit(spec)
        if streaming:
            from ray_tpu.core.object_store import ObjectRefGenerator

            return ObjectRefGenerator(spec.task_id, rt)
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._fn.__name__} cannot be called directly; "
            "use .remote()"
        )


class ActorClass:
    def __init__(self, cls, options: dict):
        self._cls = cls
        self._options = options

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs):
        rt = get_runtime()
        opts = self._options
        # validate once here so both runtimes agree — a typo'd lifetime
        # must not silently mean "non-detached" on one backend
        if opts.get("lifetime") not in (None, "detached", "non_detached"):
            raise ValueError(
                "lifetime must be 'detached' or 'non_detached', "
                f"got {opts.get('lifetime')!r}"
            )
        if getattr(rt, "is_remote", False):
            v = opts.get("max_task_retries")
            if v not in (None, 0):
                import warnings

                warnings.warn(
                    f"max_task_retries={v} is not yet supported by the "
                    "distributed cluster backend; actor methods are not "
                    "automatically retried",
                    stacklevel=2,
                )
            return rt.create_actor(
                self._cls,
                args,
                kwargs,
                resources=_resource_map(opts, is_actor=True),
                name=opts.get("name"),
                lifetime=opts.get("lifetime"),
                max_restarts=opts.get("max_restarts", 0),
                max_concurrency=opts.get("max_concurrency"),
                concurrency_groups=opts.get("concurrency_groups"),
                scheduling_strategy=opts.get("scheduling_strategy"),
                runtime_env=opts.get("runtime_env"),
            )
        from ray_tpu.cluster.pip_env import has_env

        if has_env(opts.get("runtime_env")):
            raise NotImplementedError(
                "pip/uv/conda runtime environments need per-env worker processes — "
                "run against a cluster (ray_tpu.init(address=...) or "
                "Cluster()); the in-process runtime shares one interpreter"
            )
        return actor_mod.create_actor(
            rt,
            self._cls,
            args,
            kwargs,
            resources=_resource_map(opts, is_actor=True),
            name=opts.get("name"),
            lifetime=opts.get("lifetime"),
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency"),
            concurrency_groups=opts.get("concurrency_groups"),
            scheduling_strategy=opts.get("scheduling_strategy"),
        )


def remote(*args, **options):
    """@remote decorator for functions and classes (reference:
    remote_function.py:314 / actor.py:1024)."""

    def decorate(obj):
        merged = dict(_OPTION_DEFAULTS)
        merged.update(options)
        if isinstance(obj, type):
            return ActorClass(obj, merged)
        return RemoteFunction(obj, merged)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return decorate
