"""Distributed reference counting — the ownership layer of the object plane.

Analog of the reference's ReferenceCounter
(/root/reference/src/ray/core_worker/reference_counter.h:44), redesigned for
this framework's centralized-head architecture instead of the reference's
per-owner ownership graph:

- Every process counts live ``ObjectRef`` *instances* per object id: incref
  on construction/deserialization, decref on ``__del__`` (the same hook the
  reference's Python ObjectRef uses to call RemoveLocalReference).
- A 1→0 transition enqueues the id; a per-process consumer (the in-process
  runtime's GC thread, or a cluster client/worker's ``RefFlusher``) drains
  the queue and either frees locally or reports the release to the head.
- The head is the single refcount authority (it already owns the object
  directory): it tracks per-process holds, in-flight lease pins, and
  contained-object pins, and frees shm copies + directory entries when all
  reach zero. The reference distributes this over owner workers with borrow
  protocols (WaitForRefRemoved); centralizing it removes that protocol
  entirely — a deliberate redesign, not a simplification of semantics:
  borrowers, nested refs, and lineage release all behave the same.

Serialization hooks: while a payload is being pickled, every ObjectRef
serialized into it is collected (the task-arg set the head must pin); while
bytes are unpickled, every ObjectRef constructed is collected (the borrow
set a getter must register). This mirrors the reference's serialization
context (python/ray/_private/serialization.py contained-ObjectRef capture).
"""
from __future__ import annotations

import threading
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Set

# ---------------------------------------------------------------------------
# per-process instance counting
# ---------------------------------------------------------------------------


class RefTracker:
    """Counts live ObjectRef instances per object id in this process."""

    def __init__(self) -> None:
        # RLock: decref fires from __del__, which the GC can run inside an
        # allocation that happens while incref holds the lock.
        self._lock = threading.RLock()
        self._counts: Dict[str, int] = {}
        self._zeros: deque = deque()
        self.zero_event = threading.Event()
        from ray_tpu.config import cfg

        self._debug = cfg.refcount_debug
        self._hist: Dict[str, list] = {}

    def _note(self, hex_id: str, op: str, count: int) -> None:
        import traceback

        frames = [
            f"{f.name}:{f.lineno}"
            for f in traceback.extract_stack(limit=8)[:-3]
        ]
        self._hist.setdefault(hex_id, []).append((op, count, frames))

    def history(self, hex_id: str) -> list:
        with self._lock:
            return list(self._hist.get(hex_id, ()))

    def incref(self, hex_id: str) -> None:
        with self._lock:
            self._counts[hex_id] = self._counts.get(hex_id, 0) + 1
            if self._debug:
                self._note(hex_id, "incref", self._counts[hex_id])

    def decref(self, hex_id: str) -> None:
        with self._lock:
            c = self._counts.get(hex_id, 0) - 1
            if self._debug:
                self._note(hex_id, "decref", c)
            if c > 0:
                self._counts[hex_id] = c
                return
            self._counts.pop(hex_id, None)
            self._zeros.append(hex_id)
        # debounced wake: set() on an Event takes its condition lock even
        # when already set — under a release storm that is thousands of
        # redundant lock round-trips on the hot __del__ path
        if not self.zero_event.is_set():
            self.zero_event.set()

    def count(self, hex_id: str) -> int:
        with self._lock:
            return self._counts.get(hex_id, 0)

    def all_zero(self, hex_ids) -> List[str]:
        """Subset of ``hex_ids`` with count 0, under ONE lock acquisition
        (the flusher's re-check; per-id count() calls serialize against
        the incref/decref hot path)."""
        with self._lock:
            return [h for h in hex_ids if self._counts.get(h, 0) == 0]

    def drain_zeros(self) -> List[str]:
        """Ids whose count hit zero since the last drain and is STILL zero
        (a re-incref in between cancels the release)."""
        out: List[str] = []
        with self._lock:
            self.zero_event.clear()
            seen: Set[str] = set()
            while self._zeros:
                h = self._zeros.popleft()
                if h in seen or self._counts.get(h, 0) > 0:
                    continue
                seen.add(h)
                out.append(h)
        return out

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._counts)


TRACKER = RefTracker()

# ---------------------------------------------------------------------------
# serialization / deserialization collection contexts (thread-local)
# ---------------------------------------------------------------------------

_ctx = threading.local()


def note_serialized(hex_id: str) -> None:
    s = getattr(_ctx, "ser", None)
    if s is not None:
        s.add(hex_id)


def note_deserialized(hex_id: str) -> None:
    s = getattr(_ctx, "deser", None)
    if s is not None:
        s.add(hex_id)


@contextmanager
def collect_serialized():
    """Collect the ids of every ObjectRef pickled inside the block — the
    arg set a lease submission must ask the head to pin."""
    prev = getattr(_ctx, "ser", None)
    out: Set[str] = set()
    _ctx.ser = out
    try:
        yield out
    finally:
        _ctx.ser = prev


@contextmanager
def collect_deserialized():
    """Collect the ids of every ObjectRef constructed by unpickling inside
    the block — the borrow set a getter must register with the head."""
    prev = getattr(_ctx, "deser", None)
    out: Set[str] = set()
    _ctx.deser = out
    try:
        yield out
    finally:
        _ctx.deser = prev


# ---------------------------------------------------------------------------
# per-process holder identity + release consumer
# ---------------------------------------------------------------------------

_holder_id: Optional[str] = None
_holder_lock = threading.Lock()


def get_holder_id() -> str:
    """Stable id naming this process in the head's holder table."""
    global _holder_id
    with _holder_lock:
        if _holder_id is None:
            _holder_id = f"proc-{uuid.uuid4().hex[:12]}"
        return _holder_id


def set_holder_id(holder: str) -> None:
    global _holder_id
    with _holder_lock:
        _holder_id = holder


_consumer = None
_consumer_lock = threading.Lock()


def install_consumer(consumer, replace: bool = True):
    """Install the process-wide zero-event consumer. A worker process
    installs its flusher before any nested client runtime exists; the nested
    runtime must reuse it (``replace=False`` returns the incumbent)."""
    global _consumer
    with _consumer_lock:
        if _consumer is not None and not replace:
            return _consumer
        old, _consumer = _consumer, consumer
        if old is not None and old is not consumer:
            try:
                old.stop()
            except Exception:  # noqa: BLE001
                pass
        return consumer


def current_consumer():
    return _consumer


def clear_consumer(consumer=None) -> None:
    global _consumer
    with _consumer_lock:
        if consumer is None or _consumer is consumer:
            _consumer = None


class RefFlusher:
    """Cluster-client release reporter.

    Batches 1→0 releases to the head (debounced), and sends borrow
    registrations synchronously *in order* with releases — one send lock
    serializes the wire so a stale release can never overtake a re-borrow
    (the ordering problem the reference solves with per-owner sequence
    numbers in the borrower protocol).
    """

    FLUSH_INTERVAL_S = 0.02

    def __init__(self, send: Callable[[List[str], List[str]], None], holder: str):
        self._send = send  # send(increfs, decrefs)
        self.holder = holder
        self._send_lock = threading.Lock()
        # ids this process has registered at the head (via submit/put/borrow);
        # only these owe the head a release.
        self._held_at_head: Set[str] = set()
        # releases that failed to send (transport blip): retried next flush
        self._owed: Set[str] = set()
        self._held_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="ref-flusher", daemon=True
        )
        self._thread.start()

    def note_registered(self, hex_ids) -> None:
        """Ids the head already counts for us (lease returns, puts, borrow
        reports carried in task replies)."""
        with self._held_lock:
            self._held_at_head.update(hex_ids)

    def note_registered_live(self, hex_ids) -> None:
        """Like note_registered, but safe for registrations that land
        AFTER submission (e.g. at direct-call result time): an id whose
        local count already hit zero — the caller dropped the ref before
        the head-side registration existed, so its zero event was drained
        unregistered — immediately owes the head a release."""
        fire = False
        with self._held_lock:
            for h in hex_ids:
                if TRACKER.count(h) == 0:
                    self._owed.add(h)
                    fire = True
                else:
                    self._held_at_head.add(h)
        if fire:
            TRACKER.zero_event.set()

    def is_registered(self, hex_id: str) -> bool:
        with self._held_lock:
            return hex_id in self._held_at_head

    def sync_incref(self, hex_ids) -> None:
        """Register borrows NOW (while the outer object's pin still holds) —
        called by get() paths after deserializing a value containing refs."""
        fresh = []
        with self._held_lock:
            for h in hex_ids:
                if h not in self._held_at_head:
                    self._held_at_head.add(h)
                    fresh.append(h)
        if not fresh:
            return
        with self._send_lock:
            self._send(fresh, [])

    def flush(self) -> None:
        zeros = TRACKER.drain_zeros()
        with self._held_lock:
            # the zero re-check MUST happen under _held_lock: sync_incref
            # (a re-borrow) holds it while deciding an id is already
            # registered — a snapshot taken before would race it and
            # release a ref the borrower still holds
            still_zero = set(TRACKER.all_zero(zeros))
            for h in zeros:
                if h in self._held_at_head and h in still_zero:
                    self._held_at_head.discard(h)
                    self._owed.add(h)
            # a re-borrow between flushes cancels the owed release
            rel = [h for h in self._owed if h not in self._held_at_head]
            self._owed.clear()
        if not rel:
            return
        import logging

        logging.getLogger("ray_tpu.refcount").debug(
            "flush releases %d ids", len(rel)
        )
        with self._send_lock:
            try:
                self._send([], rel)
            except Exception:  # noqa: BLE001 - transport blip: still owed
                with self._held_lock:
                    self._owed.update(
                        h for h in rel if h not in self._held_at_head
                    )
                TRACKER.zero_event.set()  # retry on the next flush tick

    def _loop(self) -> None:
        while not self._stop.is_set():
            TRACKER.zero_event.wait(timeout=1.0)
            if self._stop.is_set():
                return
            self._stop.wait(self.FLUSH_INTERVAL_S)  # debounce window
            self.flush()

    def stop(self, release_all: bool = False) -> None:
        self._stop.set()
        TRACKER.zero_event.set()  # unblock the loop
        if release_all:
            import logging
            import traceback

            with self._held_lock:
                rel = list(self._held_at_head | self._owed)
                self._held_at_head.clear()
                self._owed.clear()
            logging.getLogger("ray_tpu.refcount").debug(
                "flusher release_all: %d ids", len(rel)
            )
            if rel:
                # BOUNDED acquire: a flush thread wedged mid-send on a dead
                # head (enqueue ack-wait) holds _send_lock forever — exit
                # must not block on it. Undelivered releases are covered by
                # the head's disconnect reap of this holder's rows.
                if not self._send_lock.acquire(timeout=5.0):
                    logging.getLogger("ray_tpu.refcount").debug(
                        "flusher release_all skipped: send lock wedged"
                    )
                    return
                try:
                    self._send([], rel)
                except Exception:  # noqa: BLE001
                    pass
                finally:
                    self._send_lock.release()


def loads_tracking(flusher: "RefFlusher", data):
    """Deserialize a fetched value, registering any ObjectRefs inside it as
    borrows with the head *before* user code sees them (while the containing
    object's pin still protects them). ``data`` may be bytes or a zero-copy
    memoryview (shm arena page); the out-of-band wire format deserializes
    numpy payloads as views over it."""
    from ray_tpu.cluster import serialization as wire

    with collect_deserialized() as borrowed:
        value = wire.loads(data)
    if borrowed:
        flusher.sync_incref(sorted(borrowed))
    return value


class FreedLRU:
    """Bounded tombstone set guarding against a late seal resurrecting a
    freed object's directory entry (the reference keeps freed-object
    tombstones in the reference counter for the same race)."""

    def __init__(self, cap: int = 1 << 16):
        self._cap = cap
        self._set: Set[str] = set()
        self._order: deque = deque()
        self._lock = threading.Lock()

    def add(self, hex_id: str) -> None:
        with self._lock:
            if hex_id in self._set:
                return
            self._set.add(hex_id)
            self._order.append(hex_id)
            while len(self._order) > self._cap:
                self._set.discard(self._order.popleft())

    def __contains__(self, hex_id: str) -> bool:
        with self._lock:
            return hex_id in self._set

    def discard(self, hex_id: str) -> None:
        with self._lock:
            self._set.discard(hex_id)
