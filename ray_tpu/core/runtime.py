"""The ray_tpu runtime: nodes, leases, batched scheduling, lineage.

Single-process, multi-node-simulated runtime — the analog of the reference's
raylet + GCS + core-worker stack (/root/reference/src/ray/raylet/,
src/ray/gcs/, src/ray/core_worker/), with the crucial difference that *all*
placement decisions flow through the batched JAX kernels in
``ray_tpu.scheduler`` instead of per-request C++ scans:

- Every task/actor-creation submission becomes a *lease request* queued with
  the scheduler thread (ClusterLeaseManager::QueueAndScheduleLease analog,
  cluster_lease_manager.cc:47).
- The scheduler thread drains the queue and places the whole batch with one
  ``hybrid_schedule_batch`` call (ScheduleAndGrantLeases hot loop,
  cluster_lease_manager.cc:196 — but batched).
- Grants are admitted against each node's exact fixed-point ledger
  (grant-or-reject under a possibly-stale dense view, the reference's
  LocalResourceManager contract); rejected grants are requeued (spillback).
- Node death drops that node's objects; lost objects are rebuilt by lineage
  re-execution (ObjectRecoveryManager / TaskManager::ResubmitTask analog,
  core_worker/task_manager.h:229).

This process-level harness is also the test vehicle for multi-node scheduling
logic, mirroring how the reference tests multi-node behavior in a single
process (python/ray/cluster_utils.py:137).
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import traceback
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.scheduler import (
    ClusterView,
    HybridConfig,
    NodeResourceLedger,
    ResourceRequest,
    ResourceVocab,
    hybrid_schedule_reference,
)
from .object_store import GetTimeoutError, ObjectRef, ObjectStore, TaskError

logger = logging.getLogger("ray_tpu")

# Leases per scheduling round (the batching that makes the TPU kernel pay).
MAX_SCHEDULE_BATCH = 1024

_STREAM_END = object()  # generator-exhausted sentinel (values can be None)


def _now() -> float:
    import time

    return time.monotonic()


class ActorDiedError(Exception):
    pass


class NodeDiedError(Exception):
    pass


@dataclass
class TaskSpec:
    """A task/actor-creation/actor-method invocation (LeaseSpecification +
    TaskSpecification analog, src/ray/common/lease/)."""

    task_id: str
    func: Callable
    args: tuple
    kwargs: dict
    returns: List[ObjectRef]  # transient: emptied by submit() so queued
    # specs pin their *args* (live ObjectRef instances) but never their own
    # outputs — lineage release is what frees args when outputs die
    resources: Dict[str, float]
    name: str = ""
    kind: str = "task"  # task | actor_creation | actor_method
    actor_id: Optional[str] = None
    strategy: Any = None  # scheduling strategy object or None
    max_retries: int = 3
    retry_exceptions: bool = False
    attempt: int = 0
    # per-task runtime env override (merged over the job-level env by the
    # submitting client); {"pip": ...} entries route to env-bound workers
    runtime_env: Optional[dict] = None
    # distributed trace context {trace_id, span_id, parent_id} — minted at
    # submission, inherited by nested submissions (util/tracing.py)
    trace: Optional[dict] = None
    # return object ids; a slot is None once that output has been freed
    return_ids: List[Optional[str]] = field(default_factory=list)
    # num_returns="streaming": executor iterates the function's generator,
    # sealing each yield under stream_item_id(task_id, i); the caller
    # consumes an ObjectRefGenerator
    streaming: bool = False


@dataclass
class Node:
    """A simulated cluster node: ledger + worker pool (raylet + workers)."""

    node_id: str
    ledger: NodeResourceLedger
    pool: ThreadPoolExecutor
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    running_tasks: Dict[str, TaskSpec] = field(default_factory=dict)
    objects: set = field(default_factory=set)  # hex ids sealed on this node
    accel: Any = None  # NodeAcceleratorState: chip-index assignment


class _GcConsumer:
    """Tracker-consumer token for the in-process runtime's GC thread."""

    def __init__(self, stop_event: threading.Event):
        self._stop_event = stop_event

    def stop(self) -> None:
        self._stop_event.set()


class WorkerContext(threading.local):
    node_id: Optional[str] = None
    task_id: Optional[str] = None
    actor_id: Optional[str] = None
    accelerator_ids: Dict[str, list] = {}


_context = WorkerContext()


def get_context() -> WorkerContext:
    return _context


class Runtime:
    """Cluster-in-a-process. One instance per init()."""

    def __init__(
        self,
        num_nodes: int = 1,
        resources_per_node: Optional[Dict[str, float]] = None,
        use_device_scheduler: Optional[bool] = None,
        hybrid_config: HybridConfig = HybridConfig(),
    ):
        self.vocab = ResourceVocab()
        self.view = ClusterView(self.vocab)
        native = None
        from ray_tpu.config import cfg

        if cfg.native_store:
            try:
                from ray_tpu.native import NativeObjectStore

                native = NativeObjectStore(
                    capacity=int(
                        cfg.store_bytes
                    )
                )
            except Exception:  # noqa: BLE001 - toolchain missing → in-proc only
                logger.warning("native object store unavailable; using in-process")
        self.native_store = native
        self.store = ObjectStore(native)
        self.nodes: Dict[str, Node] = {}
        self.hybrid_config = hybrid_config
        if use_device_scheduler is None:
            from ray_tpu.scheduler.device import device_scheduler_default

            use_device_scheduler = device_scheduler_default()
        self.use_device_scheduler = use_device_scheduler
        from ray_tpu.scheduler.device import LazyDeviceState

        self._lazy_device = LazyDeviceState(use_device_scheduler)
        self._parked_at_change = -1
        self._last_park_retry = 0.0
        self._rng = np.random.default_rng(0)
        # streaming-generator state: task_id -> {"items": [hex...],
        # "done": bool} (num_returns="streaming" tasks; cluster analog
        # lives on the head)
        self._streams: Dict[str, dict] = {}
        # tombstones for abandoned streams: popping the live state must
        # not let a lineage re-execution of the same task resurrect a
        # fresh un-abandoned stream and drive the generator with no
        # consumer (one small string per abandoned stream)
        self._abandoned_streams: set = set()
        self._stream_cv = threading.Condition()
        self._spread_rr = 0  # SPREAD round-robin cursor
        self._label_rr = 0  # label-selector tie-break cursor
        self._seed_counter = itertools.count(1)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[TaskSpec] = []
        self._infeasible: List[TaskSpec] = []
        self._dep_waiting: List[TaskSpec] = []  # args not sealed yet
        self._lineage: Dict[str, TaskSpec] = {}  # object hex -> creating spec
        self._actors: Dict[str, "ActorState"] = {}
        self._named_actors: Dict[str, str] = {}
        self._pgs: Dict[str, Any] = {}  # pg_id -> PlacementGroupState
        self._pending_pgs: List[Any] = []  # PG states awaiting placement
        self._dirty = False
        self._shutdown = False
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="ray_tpu-scheduler", daemon=True
        )
        # automatic object GC (ReferenceCounter analog): drains instance-count
        # zeros from the process tracker and frees store entries + lineage
        from .refcount import FreedLRU, install_consumer

        self._freed = FreedLRU()
        self._gc_stop = threading.Event()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, name="ray_tpu-gc", daemon=True
        )
        install_consumer(_GcConsumer(self._gc_stop))
        self.metrics: Dict[str, int] = {
            "tasks_submitted": 0,
            "tasks_finished": 0,
            "tasks_failed": 0,
            "leases_spilled_back": 0,
            "sched_rounds": 0,
        }
        from .events import TaskEventBuffer

        self.events = TaskEventBuffer()
        if resources_per_node is None:
            resources_per_node = {"CPU": 8, "memory": float(4 << 30)}
        for i in range(num_nodes):
            self.add_node(resources_per_node)
        self._sched_thread.start()
        self._gc_thread.start()

    # ------------------------------------------------------------------
    # automatic object GC (reference_counter.h:44 analog)
    # ------------------------------------------------------------------
    def _gc_loop(self) -> None:
        from .refcount import TRACKER

        while not self._gc_stop.is_set():
            TRACKER.zero_event.wait(timeout=1.0)
            if self._gc_stop.is_set():
                return
            for hex_id in TRACKER.drain_zeros():
                try:
                    self._free_local(hex_id)
                except Exception:  # noqa: BLE001 - GC must survive
                    logger.exception("object GC failed for %s", hex_id)

    def _free_local(self, hex_id: str) -> None:
        """No live handle remains for this object: drop the sealed value
        (or flag an unsealed entry to be dropped at seal) and release its
        lineage — which releases the creating task's argument refs, so
        frees cascade exactly like the reference's lineage release
        (reference_counter.h ReleaseLineageReferences)."""
        removed = self.store.free_id(hex_id)
        spec = self._lineage.pop(hex_id, None)
        if spec is not None and removed:
            # tombstone the slot: a lineage re-execution of a sibling output
            # must not resurrect this one
            for i, rid in enumerate(spec.return_ids):
                if rid == hex_id:
                    spec.return_ids[i] = None
        if removed:
            self._freed.add(hex_id)
            for node in self.nodes.values():
                node.objects.discard(hex_id)

    def _seal_id(self, node: Optional[Node], hex_id: Optional[str], value, is_error=False) -> None:
        """Seal one output by id, honoring freed tombstones and
        dropped-before-sealed outputs."""
        if hex_id is None or hex_id in self._freed:
            return
        if node is not None:
            node.objects.add(hex_id)
        if self.store.seal_id(hex_id, value, is_error):
            self._free_local(hex_id)

    # ------------------------------------------------------------------
    # membership (GcsNodeManager analog)
    # ------------------------------------------------------------------
    def add_node(
        self,
        resources: Dict[str, float],
        labels: Optional[Dict[str, str]] = None,
    ) -> str:
        from ray_tpu.scheduler.instances import NodeAcceleratorState

        node_id = uuid.uuid4().hex[:16]
        num_workers = max(1, int(resources.get("CPU", 1)))
        node = Node(
            node_id=node_id,
            ledger=NodeResourceLedger(self.vocab, resources),
            pool=ThreadPoolExecutor(
                max_workers=num_workers, thread_name_prefix=f"worker-{node_id[:6]}"
            ),
            labels=dict(labels or {}),
            accel=NodeAcceleratorState(resources),
        )
        with self._cond:
            self.nodes[node_id] = node
            self.view.add_node(node_id, resources, labels)
            # new capacity may unblock infeasible leases and pending PGs
            self._dirty = True
            self._pending.extend(self._infeasible)
            self._infeasible.clear()
            self._cond.notify_all()
        return node_id

    def kill_node(self, node_id: str) -> None:
        """Simulated node failure (test chaos hook, like RayletKiller,
        /root/reference/python/ray/_private/test_utils.py:1408)."""
        with self._cond:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            self.view.remove_node(node_id)
            lost_objects = list(node.objects)
            node.objects.clear()
            running = list(node.running_tasks.values())
            node.running_tasks.clear()
            # Actors on this node die.
            for actor in list(self._actors.values()):
                if actor.node_id == node_id and actor.alive:
                    actor.mark_died(restart=True)
            self._cond.notify_all()
        node.pool.shutdown(wait=False, cancel_futures=True)
        # Drop the node's objects; lineage rebuilds them on demand.
        for hex_id in lost_objects:
            self._invalidate_object(hex_id)
        # Resubmit tasks that were running there.
        for spec in running:
            if spec.attempt < spec.max_retries:
                spec.attempt += 1
                self.metrics["leases_spilled_back"] += 1
                self._enqueue(spec)
            else:
                err = NodeDiedError(f"node {node_id} died running {spec.name}")
                for rid in spec.return_ids:
                    self._seal_id(None, rid, err, is_error=True)

    def _invalidate_object(self, hex_id: str) -> None:
        if hex_id in self._freed:
            return  # nobody holds it anymore; no point reconstructing
        spec = self._lineage.get(hex_id)
        if spec is not None and (
            spec.kind != "task" or spec.attempt >= spec.max_retries
        ):
            # Lineage exhausted (or not a re-executable plain task): the
            # object is permanently lost — fail pending gets.
            if hex_id in spec.return_ids and self.store.contains(
                ObjectRef.weak(hex_id)
            ):
                return  # already sealed elsewhere (e.g. resubmitted copy won)
            from .object_store import ObjectLostError

            self._seal_id(
                None,
                hex_id,
                ObjectLostError(
                    f"object {hex_id} lost with its node; lineage retries "
                    f"exhausted ({spec.attempt}/{spec.max_retries})"
                ),
                is_error=True,
            )
            return
        with self.store._lock:
            entry = self.store._objects.get(hex_id)
            if entry is not None and entry.event.is_set():
                entry.event.clear()
                entry.value = None
        if spec is not None:
            clone = TaskSpec(
                task_id=uuid.uuid4().hex[:16],
                func=spec.func,
                args=spec.args,
                kwargs=spec.kwargs,
                returns=[],
                return_ids=list(spec.return_ids),
                resources=spec.resources,
                name=spec.name,
                kind=spec.kind,
                actor_id=spec.actor_id,
                strategy=spec.strategy,
                max_retries=spec.max_retries,
                retry_exceptions=spec.retry_exceptions,
                attempt=spec.attempt + 1,
            )
            for rid in clone.return_ids:
                if rid is not None:
                    self._lineage[rid] = clone  # retry budget advances
            self._enqueue(clone)

    # ------------------------------------------------------------------
    # submission (NormalTaskSubmitter analog)
    # ------------------------------------------------------------------
    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        from ray_tpu.cluster.pip_env import has_env

        if has_env(spec.runtime_env):
            raise NotImplementedError(
                "pip/uv/conda runtime environments need per-env worker processes — "
                "run against a cluster (ray_tpu.init(address=...) or "
                "Cluster()); the in-process runtime shares one interpreter"
            )
        refs = spec.returns
        spec.return_ids = [r.hex for r in refs]
        # the queued/lineage spec keeps only ids: the user's handles are the
        # sole owners of the outputs (dropping them all → automatic GC)
        spec.returns = []
        for ref in refs:
            self.store.create(ref, creating_task=spec.task_id)
            self._lineage[ref.hex] = spec
        self.metrics["tasks_submitted"] += 1
        if spec.streaming:
            self.register_stream(spec.task_id)
        from ray_tpu.util import tracing

        if spec.trace is None:
            spec.trace = tracing.child_context(spec.task_id)
        self.events.record(
            spec.task_id, spec.name, "SUBMITTED",
            **tracing.event_args(spec.trace)
        )
        self._enqueue(spec)
        return refs

    def _enqueue(self, spec: TaskSpec) -> None:
        with self._cond:
            if self._shutdown:
                return
            self._pending.append(spec)
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # the batched scheduler (ScheduleAndGrantLeases analog)
    # ------------------------------------------------------------------
    @property
    def device_state(self):
        """Lazy DeviceSchedulerState with bring-up timeout (see
        scheduler/device.py LazyDeviceState): a wedged accelerator backend
        degrades to the host golden model instead of freezing init."""
        return self._lazy_device.get()

    def _unready_args(self, spec: TaskSpec) -> List[ObjectRef]:
        """Top-level ObjectRef args not yet sealed (the set the reference's
        LeaseDependencyManager waits on before making a lease dispatchable,
        lease_dependency_manager.h:41)."""
        refs = [a for a in spec.args if isinstance(a, ObjectRef)]
        refs += [v for v in spec.kwargs.values() if isinstance(v, ObjectRef)]
        return [r for r in refs if not self.store.contains(r)]

    def _admit_dep_ready(self) -> List[TaskSpec]:
        ready = []
        still = []
        for spec in self._dep_waiting:
            (ready if not self._unready_args(spec) else still).append(spec)
        self._dep_waiting = still
        return ready

    def _scheduler_loop(self) -> None:
        while True:
            with self._cond:
                while (
                    not self._pending and not self._dirty and not self._shutdown
                ):
                    self._cond.wait(timeout=0.5)
                    # Lost-wakeup backstop: a spec parked *after* the release
                    # event that would have drained it would otherwise sleep
                    # until the next cluster change. Retry parked work only
                    # when the view actually moved since the last drain, so
                    # truly-infeasible specs don't spin the kernel at 2 Hz.
                    self._maybe_unpark_locked()
                    if self._dep_waiting:
                        self._pending.extend(self._admit_dep_ready())
                if self._shutdown:
                    return
                # parked work also retries while NEW submissions keep the
                # queue hot (a steady submit stream would otherwise starve
                # every parked spec — same discipline as the cluster head)
                self._maybe_unpark_locked()
                self._dirty = False
                take = min(len(self._pending), MAX_SCHEDULE_BATCH)
                batch = self._admit_dep_ready() + self._pending[:take]
                del self._pending[:take]
                # dependency-aware dispatch: leases with unsealed args wait
                # here holding NOTHING (no resources, no worker thread) —
                # ready leases interleave past them
                waiting = [s for s in batch if self._unready_args(s)]
                if waiting:
                    w = {id(s) for s in waiting}
                    batch = [s for s in batch if id(s) not in w]
                    self._dep_waiting.extend(waiting)
            try:
                self._try_schedule_pgs()
                if batch:
                    self._schedule_batch(batch)
            except Exception:  # pragma: no cover - scheduler must survive
                logger.exception("scheduler round failed; requeueing batch")
                with self._cond:
                    self._pending.extend(batch)

    def register_pg(self, state) -> None:
        """Queue a placement group for scheduling (SchedulePendingPlacementGroups
        analog, gcs_placement_group_manager.cc:300)."""
        with self._cond:
            self._pgs[state.id] = state
            self._pending_pgs.append(state)
            self._dirty = True
            self._cond.notify_all()

    def notify_resources_changed(self) -> None:
        # completions only NOTIFY; the scheduler loop's capacity-capped
        # unpark retries parked work. Draining the whole parked queue here
        # (pre-r5) re-scheduled every parked spec on every completion —
        # O(parked²) churn under a deep backlog (see cluster/head.py).
        with self._cond:
            # some callers free capacity the ClusterView can't see (PG
            # bundle releases mutate bundle-local books only): bump the
            # change counter HERE so the change-gated unpark always fires
            # for an explicit resource-changed notification
            self.view.change_counter += 1
            self._dirty = True
            self._cond.notify_all()

    def _maybe_unpark_locked(self) -> None:
        """Rate-limited, change-gated unpark. Caller holds self._cond."""
        if self._infeasible and (
            (
                self.view.change_counter != self._parked_at_change
                and _now() - self._last_park_retry > 0.02
            )
            # liveness fallback: capacity can free without a view change
            # (PG bundle books are bundle-local) — retry parked work at
            # 1 Hz regardless, bounded by the per-shape cap
            or _now() - self._last_park_retry > 1.0
        ):
            self._parked_at_change = self.view.change_counter
            self._last_park_retry = _now()
            self._unpark_grantable()

    def _unpark_grantable(self) -> None:
        """Move parked specs back to pending, capped per resource shape
        at what the view could grant (scheduler/unpark.py, shared with
        the cluster head). Caller holds self._cond."""
        from ray_tpu.scheduler.unpark import (
            UNPARK_SLACK,
            select_unparkable_resilient,
        )

        parked = self._infeasible
        if not parked:
            return
        if len(parked) <= UNPARK_SLACK:
            self._pending.extend(parked)
            self._infeasible = []
            return
        # slot estimation on the resident device arrays when the XLA
        # scheduler is already up (one batched kernel instead of a host
        # scan per shape) — mirrors the cluster head's unpark path
        from ray_tpu.config import cfg as _cfg

        device_state = self._lazy_device._result
        slots_fn = None
        _, a0, al0 = self.view.active_arrays()
        if device_state is not None and _cfg.sched_unpark_device:
            try:
                device_state.sync(self.view)
                slots_fn = device_state.shape_slots
            except Exception:  # noqa: BLE001 - scheduler must survive
                logger.exception("device unpark sync failed; host scan")
                device_state.invalidate()
        if slots_fn is None:
            a0, al0 = a0.copy(), al0.copy()
        def _refetch():
            _, f0, fl0 = self.view.active_arrays()
            return f0.copy(), fl0.copy()

        take, keep = select_unparkable_resilient(
            parked,
            a0,
            al0,
            device_state=device_state,
            slots_fn=slots_fn,
            refetch=_refetch,
            # "DEFAULT" routes through the hybrid kernels like None —
            # only real placement constraints skip the capacity math
            is_constrained=lambda s: s.strategy is not None
            and s.strategy != "DEFAULT",
            resources_of=lambda s: s.resources,
            request_of=lambda s: ResourceRequest.from_map(
                self.vocab, s.resources
            ),
        )
        self._pending.extend(take)
        self._infeasible = keep

    def _try_schedule_pgs(self) -> None:
        with self._cond:
            pending = list(self._pending_pgs)
        for state in pending:
            if state.removed:
                with self._cond:
                    if state in self._pending_pgs:
                        self._pending_pgs.remove(state)
                continue
            if state.try_schedule():
                with self._cond:
                    if state in self._pending_pgs:
                        self._pending_pgs.remove(state)
                    # PG-waiting leases were parked as infeasible; retry them.
                    self._pending.extend(self._infeasible)
                    self._infeasible.clear()
                    self._cond.notify_all()

    def _schedule_batch(self, batch: List[TaskSpec]) -> None:
        self.metrics["sched_rounds"] += 1
        # Split out strategy-constrained leases; they bypass the hybrid kernel
        # (the reference dispatches them to other policies —
        # composite_scheduling_policy.cc).
        hybrid_batch: List[TaskSpec] = []
        for spec in batch:
            target = self._strategy_target(spec)
            if target is _HYBRID:
                hybrid_batch.append(spec)
            elif target is _FAIL:
                self.metrics["tasks_failed"] += 1
                err = TaskError(
                    NodeDiedError(
                        f"task {spec.name}: hard scheduling constraint can "
                        "never be satisfied (target node is dead/unknown)"
                    ),
                    spec.name,
                )
                for rid in spec.return_ids:
                    self._seal_id(None, rid, err, is_error=True)
            elif target is None:
                self._park_infeasible(spec)
            else:
                node_id, via_pg = target
                self._grant_or_requeue(spec, node_id, via_pg=via_pg)
        if not hybrid_batch:
            return

        totals = avail = alive = None
        # lazy XLA init outside the lock (a wedged backend must not freeze
        # every thread that needs the view)
        device_state = self.device_state
        with self._lock:
            n = self.view.num_nodes
            r = self.view.totals.shape[1]
            if device_state is not None and n > 0:
                device_state.sync(self.view)
            else:
                totals, avail, alive = self.view.active_arrays()
        if n == 0:
            for spec in hybrid_batch:
                self._park_infeasible(spec)
            return
        sched: List[TaskSpec] = []
        dense_rows: List[np.ndarray] = []
        for spec in hybrid_batch:
            req = ResourceRequest.from_map(self.vocab, spec.resources)
            if any(c >= r and fp > 0 for c, fp in req.demands.items()):
                # demands a resource no node carries — unplaceable for now
                self._park_infeasible(spec)
            else:
                sched.append(spec)
                dense_rows.append(req.dense(r))
        if not sched:
            return
        demands = np.stack(dense_rows)
        if device_state is not None:
            nodes_idx = device_state.schedule(
                demands, spread_threshold=self.hybrid_config.spread_threshold
            )
            granted = nodes_idx >= 0
        else:
            prefer = np.zeros(len(sched), dtype=np.int32)
            force_spill = np.zeros(len(sched), dtype=bool)
            nodes_idx, granted, _ = hybrid_schedule_reference(
                totals,
                avail,
                alive,
                demands,
                prefer,
                force_spill,
                config=self.hybrid_config,
                rng=self._rng,
            )
        for spec, row, ok in zip(sched, nodes_idx, granted):
            if row < 0 or not ok:
                # Infeasible anywhere, or feasible but no node has the
                # resources free right now: park until a release/new node
                # notifies (the reference queues at the target raylet,
                # local_lease_manager.h:39). The ledger's grant-or-reject in
                # _grant_or_requeue corrects any stale-view optimism.
                self._park_infeasible(spec)
            else:
                self._grant_or_requeue(spec, self.view.node_id(int(row)))

    _SENTINEL = object()

    def _pick_spread_node(
        self, spec: TaskSpec, random: bool = False
    ) -> Optional[str]:
        """Distinct SPREAD (round-robin) / RANDOM (uniform) over feasible
        alive nodes (spread_scheduling_policy.cc:26 /
        random_scheduling_policy.cc analogs)."""
        req = ResourceRequest.from_map(self.vocab, spec.resources)
        with self._lock:
            avail, alive = self.view.active_arrays()[1:]
            n = self.view.num_nodes
            r = avail.shape[1] if n else 0
            if n == 0 or any(
                c >= r and fp > 0 for c, fp in req.demands.items()
            ):
                return None  # no nodes / unknown resource: park infeasible
            d = req.dense(r)
            feasible = (avail >= d).all(axis=1) & alive
            if random:
                cand = np.flatnonzero(feasible)
                if cand.size == 0:
                    return None
                return self.view.node_id(int(self._rng.choice(cand)))
            order = np.roll(np.arange(n), -self._spread_rr)
            cand = order[feasible[order]]
            if cand.size == 0:
                return None
            row = int(cand[0])
            self._spread_rr = (row + 1) % n
            return self.view.node_id(row)

    def _pick_labeled_node(self, strat, resources) -> Optional[str]:
        """Label-selector placement (node_label_scheduling_policy.cc
        analog): hard selectors + resource feasibility filter, soft
        selectors prefer, ties round-robin."""
        from ray_tpu.scheduler.labels import match_labels

        req = ResourceRequest.from_map(self.vocab, resources)
        with self._lock:
            hard = [
                n.node_id
                for n in self.nodes.values()
                if n.alive
                and match_labels(n.labels, strat.hard)
                and n.ledger.is_available(req)
            ]
            preferred = [
                nid
                for nid in hard
                if match_labels(self.nodes[nid].labels, strat.soft)
            ]
        pool = preferred or hard
        if not pool:
            return None
        self._label_rr += 1
        return pool[self._label_rr % len(pool)]

    def _strategy_target(self, spec: TaskSpec):
        """Resolve scheduling strategies. Returns _HYBRID, None (infeasible
        now), or (node_id, via_pg) to dispatch directly."""
        from .scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
            NodeLabelSchedulingStrategy,
            PlacementGroupSchedulingStrategy,
        )

        strat = spec.strategy
        if strat is None or strat == "DEFAULT":
            return _HYBRID
        if strat in ("SPREAD", "RANDOM"):
            target = self._pick_spread_node(spec, random=strat == "RANDOM")
            return None if target is None else (target, None)
        if isinstance(strat, NodeLabelSchedulingStrategy):
            target = self._pick_labeled_node(strat, spec.resources)
            if target is None:
                return None if strat.hard else _HYBRID
            return (target, None)
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            node = self.nodes.get(strat.node_id)
            if node is not None and node.alive:
                return (strat.node_id, None)
            # Hard affinity to a dead/unknown node can never succeed — fail
            # fast (the reference raises an unschedulable error).
            return _HYBRID if strat.soft else _FAIL
        if isinstance(strat, PlacementGroupSchedulingStrategy):
            pg = self._pgs.get(strat.placement_group.id)
            if pg is None or not pg.ready_event.is_set():
                return None  # wait for PG (requeued when PG commits)
            picked = pg.pick_bundle(
                strat.placement_group_bundle_index,
                ResourceRequest.from_map(self.vocab, spec.resources),
            )
            if picked is None:
                return None
            node_id, bundle_idx = picked
            return (node_id, (pg.id, bundle_idx))
        return _HYBRID

    def _park_infeasible(self, spec: TaskSpec) -> None:
        with self._cond:
            self._infeasible.append(spec)

    def requeue_parked(self) -> None:
        """Re-test infeasible/PG-waiting leases (cluster state changed)."""
        with self._cond:
            self._pending.extend(self._infeasible)
            self._infeasible.clear()
            self._cond.notify_all()

    def _grant_or_requeue(
        self, spec: TaskSpec, node_id: str, via_pg: Optional[tuple] = None
    ) -> None:
        node = self.nodes.get(node_id)
        req = ResourceRequest.from_map(self.vocab, spec.resources)
        if node is None or not node.alive:
            self._enqueue(spec)
            return
        if via_pg is not None:
            pg_id, bundle_idx = via_pg
            pg = self._pgs.get(pg_id)
            if pg is None or not pg.try_allocate(bundle_idx, req):
                self._park_infeasible(spec)
                return
        elif not node.ledger.try_allocate(req):
            # Stale dense view → grant rejected → spill back to the queue
            # (grant-or-reject, local_lease_manager.h:39-61).
            self.metrics["leases_spilled_back"] += 1
            self.view.update_available(node_id, node.ledger.avail_map())
            self._enqueue(spec)
            return
        # chip-index assignment on top of the scalar grant
        assign = node.accel.allocate(spec.resources) if node.accel else {}
        if assign is None:  # fractional-share fragmentation
            if via_pg is not None:
                pg.release(bundle_idx, req)
            else:
                node.ledger.release(req)
            self._park_infeasible(spec)
            return
        if via_pg is None:
            self.view.update_available(node_id, node.ledger.avail_map())
        node.running_tasks[spec.task_id] = spec
        self.events.record(spec.task_id, spec.name, "SCHEDULED", node.node_id)
        node.pool.submit(self._execute, spec, node, req, via_pg, assign)

    # ------------------------------------------------------------------
    # execution (TaskReceiver analog)
    # ------------------------------------------------------------------
    def _execute(
        self,
        spec: TaskSpec,
        node: Node,
        req: ResourceRequest,
        via_pg: Optional[tuple],
        assign: Optional[dict] = None,
    ) -> None:
        _context.node_id = node.node_id
        _context.task_id = spec.task_id
        _context.actor_id = spec.actor_id
        _context.accelerator_ids = {
            name: [i for i, _ in a] for name, a in (assign or {}).items()
        }
        actor_holds_resources = False
        assign_held = False
        from ray_tpu.util import tracing

        self.events.record(
            spec.task_id, spec.name, "RUNNING", node.node_id,
            **tracing.event_args(spec.trace)
        )
        _trace_token = tracing.install(spec.trace)
        try:
            args, kwargs = self._resolve_args(spec.args, spec.kwargs)
            result = spec.func(*args, **kwargs)
            if spec.kind == "actor_creation":
                state = self._actors[spec.actor_id]
                # the actor keeps its chip assignment for life even when the
                # scalar resources came from a PG bundle (the bundle is
                # released at creation end, the silicon is not)
                state.on_created(
                    node.node_id,
                    result,
                    (node.node_id, None if via_pg else req, assign),
                )
                actor_holds_resources = via_pg is None
                assign_held = True
                self._seal_results(spec, node, spec.actor_id)
            elif spec.streaming:
                self._run_streaming(spec, node, result)
            else:
                self._seal_results(spec, node, result)
            self.metrics["tasks_finished"] += 1
            self.events.record(
                spec.task_id, spec.name, "FINISHED", node.node_id,
                **tracing.event_args(spec.trace)
            )
        except BaseException as exc:  # noqa: BLE001 - task errors are values
            if spec.retry_exceptions and spec.attempt < spec.max_retries:
                spec.attempt += 1
                self._enqueue(spec)
            else:
                self.metrics["tasks_failed"] += 1
                self.events.record(
                    spec.task_id, spec.name, "FAILED", node.node_id,
                    error=repr(exc),
                )
                err = TaskError(exc, spec.name or spec.task_id)
                err.__cause__ = exc
                for rid in spec.return_ids:
                    self._seal_id(None, rid, err, is_error=True)
                if spec.streaming:
                    self._fail_stream(spec.task_id, err)
                if spec.kind == "actor_creation":
                    state = self._actors.get(spec.actor_id)
                    if state is not None:
                        state.mark_died(restart=False)
                logger.debug(
                    "task %s failed:\n%s", spec.name, traceback.format_exc()
                )
        finally:
            tracing.uninstall(_trace_token)
            node.running_tasks.pop(spec.task_id, None)
            if not node.alive or actor_holds_resources:
                pass  # dropped with the node / held for the actor lifetime
            elif via_pg is not None:
                pg_id, bundle_idx = via_pg
                pg = self._pgs.get(pg_id)
                if pg is not None:
                    pg.release(bundle_idx, req)
                if assign and node.accel and not assign_held:
                    node.accel.release(assign)
                self.notify_resources_changed()
            else:
                node.ledger.release(req)
                if assign and node.accel and not assign_held:
                    node.accel.release(assign)
                with self._cond:
                    self.view.update_available(node.node_id, node.ledger.avail_map())
                    # freed capacity may unblock queued/infeasible leases:
                    # notify only — the scheduler loop's capacity-capped
                    # unpark retries parked work (O(parked²) otherwise)
                    self._dirty = True
                    self._cond.notify_all()
            _context.node_id = None
            _context.task_id = None
            _context.actor_id = None
            _context.accelerator_ids = {}

    # ------------------------------------------------------------------
    # actor creation (GcsActorScheduler analog)
    # ------------------------------------------------------------------
    def _submit_actor_creation(self, state, strategy=None) -> None:
        ready = ObjectRef.new(owner="actor")
        self.store.create(ready)
        spec = TaskSpec(
            task_id=uuid.uuid4().hex[:16],
            func=state.cls,
            args=state.ctor_args,
            kwargs=state.ctor_kwargs,
            returns=[ready],
            resources=state.resources,
            name=f"{state.cls.__name__}.__init__",
            kind="actor_creation",
            actor_id=state.actor_id,
            strategy=strategy,
            max_retries=0,
        )
        state.creation_ref = ready
        state.creation_strategy = strategy
        self.submit(spec)

    def _resubmit_actor_creation(self, state) -> None:
        self._submit_actor_creation(state, getattr(state, "creation_strategy", None))

    def _resolve_args(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict]:
        """Inline ObjectRef arguments (DependencyResolver analog)."""
        res_args = tuple(
            self.get_object(a) if isinstance(a, ObjectRef) else a for a in args
        )
        res_kwargs = {
            k: self.get_object(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return res_args, res_kwargs

    def _run_streaming(self, spec: TaskSpec, node: Node, gen: Any) -> None:
        """Drive a ``num_returns="streaming"`` task (lineage registered:
        tasks re-execute on object loss)."""
        self._drive_stream(spec.task_id, node, gen, lineage_spec=spec)

    def run_actor_stream(self, task_id: str, node_id: str, gen: Any) -> None:
        """Drive a streaming ACTOR-method call (no lineage — actor
        methods are not re-executable)."""
        self._drive_stream(task_id, self.nodes.get(node_id), gen)

    def register_stream(self, task_id: str) -> None:
        """Stream state exists from SUBMISSION (cluster-head parity): an
        abandon arriving before the executor starts must stick, or a
        dropped generator would later drive to completion on the
        executor — wedging a sync actor's only thread forever."""
        with self._stream_cv:
            self._streams.setdefault(
                task_id, {"items": [], "done": False}
            )
            self._stream_cv.notify_all()

    def _drive_stream(
        self, task_id: str, node, gen: Any, lineage_spec=None
    ) -> None:
        """Seal every yield as its own object under
        stream_item_id(task_id, i) and publish it to the stream state
        consumers long-poll via ``stream_next``. Item appends are
        idempotent by index, so a retried generator re-seals the same
        ids without duplicating stream entries."""
        from ray_tpu.cluster.common import stream_item_id

        if not hasattr(gen, "__next__"):
            gen = iter(gen)
        idx = 0
        while True:
            with self._stream_cv:
                if task_id in self._abandoned_streams:
                    # abandoned before (or during a re-execution of) this
                    # drive: never resurrect a consumer-less stream
                    try:
                        gen.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._streams.pop(task_id, None)
                    self._stream_cv.notify_all()
                    return
                st = self._streams.setdefault(
                    task_id, {"items": [], "done": False}
                )
                if st.get("abandoned"):
                    # consumer gone (possibly before our first yield):
                    # stop producing instead of running the generator out
                    try:
                        gen.close()
                    except Exception:  # noqa: BLE001
                        pass
                    self._abandoned_streams.add(task_id)
                    self._streams.pop(task_id, None)
                    self._stream_cv.notify_all()
                    return
            value = next(gen, _STREAM_END)
            if value is _STREAM_END:
                break
            oid = stream_item_id(task_id, idx)
            if lineage_spec is not None:
                self._lineage[oid] = lineage_spec
            self._seal_id(node, oid, value)
            with self._stream_cv:
                st = self._streams.setdefault(
                    task_id, {"items": [], "done": False}
                )
                if idx == len(st["items"]):
                    st["items"].append(oid)
                self._stream_cv.notify_all()
            idx += 1
        with self._stream_cv:
            st = self._streams.setdefault(
                task_id, {"items": [], "done": False}
            )
            st["done"] = True
            if st.get("abandoned"):
                self._streams.pop(task_id, None)
            self._stream_cv.notify_all()

    def _fail_stream(self, task_id: str, err: Any) -> None:
        """Mid-stream failure, retries exhausted: the NEXT item the
        consumer sees is a ref whose get() raises (reference generator
        semantics), then the stream ends."""
        from ray_tpu.cluster.common import stream_item_id

        with self._stream_cv:
            st = self._streams.setdefault(
                task_id, {"items": [], "done": False}
            )
            if not st["done"]:
                oid = stream_item_id(task_id, len(st["items"]))
                self._seal_id(None, oid, err, is_error=True)
                st["items"].append(oid)
                st["done"] = True
            self._stream_cv.notify_all()

    def stream_next(
        self, task_id: str, index: int, timeout: Optional[float]
    ) -> Optional[ObjectRef]:
        """Blocking fetch of stream item ``index``; None = stream ended
        before it (StopIteration for the caller's generator)."""
        deadline = None if timeout is None else _now() + timeout
        with self._stream_cv:
            while True:
                st = self._streams.get(task_id)
                if st is not None:
                    if index < len(st["items"]):
                        return ObjectRef(st["items"][index], owner=task_id)
                    if st["done"]:
                        # fully drained: drop the state (it would leak one
                        # entry per streaming call otherwise)
                        self._streams.pop(task_id, None)
                        return None
                elif self._shutdown:
                    return None
                wait_s = 0.5
                if deadline is not None:
                    wait_s = min(wait_s, deadline - _now())
                    if wait_s <= 0:
                        raise GetTimeoutError(
                            f"stream {task_id} item {index} not ready"
                        )
                self._stream_cv.wait(timeout=wait_s)

    def stream_abandon(self, task_id: str) -> None:
        """Consumer dropped the generator: stop production and make the
        state GC-able."""
        with self._stream_cv:
            self._abandoned_streams.add(task_id)
            st = self._streams.get(task_id)
            if st is not None and st["done"]:
                self._streams.pop(task_id, None)
            else:
                st = self._streams.setdefault(
                    task_id, {"items": [], "done": False}
                )
                st["abandoned"] = True
            self._stream_cv.notify_all()

    def _seal_results(self, spec: TaskSpec, node: Node, result: Any) -> None:
        rids = spec.return_ids
        if len(rids) == 1:
            values: Sequence[Any] = [result]
        else:
            values = tuple(result)
            if len(values) != len(rids):
                raise ValueError(
                    f"task {spec.name} returned {len(values)} values, "
                    f"expected {len(rids)}"
                )
        for rid, value in zip(rids, values):
            self._seal_id(node, rid, value)

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put_object(self, value: Any) -> ObjectRef:
        ref = ObjectRef.new(owner=_context.task_id or "driver")
        self.store.create(ref)
        self.store.seal(ref, value)
        node_id = _context.node_id
        if node_id and node_id in self.nodes:
            self.nodes[node_id].objects.add(ref.hex)
        return ref

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        # Lost objects were either resubmitted by _invalidate_object (lineage
        # reconstruction, object_recovery_manager.h:41) — in which case this
        # blocks until the re-execution seals — or sealed with ObjectLostError.
        return self.store.get(ref, timeout)

    def free_objects(self, refs: List[ObjectRef]) -> None:
        """Manual force-free (ray._private.internal_api.free analog); the
        automatic GC normally makes this unnecessary."""
        for r in refs:
            self._free_local(r.hex)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        from .refcount import TRACKER, clear_consumer

        self._gc_stop.set()
        TRACKER.zero_event.set()
        clear_consumer()
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        for actor in list(self._actors.values()):
            actor.stop()
        for node in self.nodes.values():
            node.pool.shutdown(wait=False, cancel_futures=True)
        self._sched_thread.join(timeout=2)
        if self.native_store is not None:
            self.native_store.close(unlink=True)

    # introspection (ray.nodes / state API analog)
    def nodes_info(self) -> List[Dict[str, Any]]:
        return [
            {
                "NodeID": n.node_id,
                "Alive": n.alive,
                "Resources": n.ledger.total_map(),
                "Available": n.ledger.avail_map(),
                "Labels": dict(n.labels),
            }
            for n in self.nodes.values()
        ]

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.ledger.total_map().items():
                out[k] = out.get(k, 0.0) + v
        return out

    def pending_resource_demands(self) -> List[Dict[str, float]]:
        """Resource shapes the cluster cannot currently place — what the
        autoscaler sees (GcsAutoscalerStateManager::HandleGetClusterResourceState
        analog, gcs_autoscaler_state_manager.cc:48)."""
        out: List[Dict[str, float]] = []
        with self._cond:
            for spec in self._pending + self._infeasible:
                if spec.resources:
                    out.append(dict(spec.resources))
            for pg in self._pending_pgs:
                if not pg.removed:
                    out.extend(dict(b) for b in pg.bundle_specs)
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.ledger.avail_map().items():
                out[k] = out.get(k, 0.0) + v
        return out


_HYBRID = object()
_FAIL = object()

_runtime: Optional[Runtime] = None
_runtime_lock = threading.Lock()


def get_runtime() -> Runtime:
    global _runtime
    if _runtime is None:
        # Inside a cluster worker process the head address is in the env —
        # nested ray_tpu API calls connect as a client automatically (the
        # reference's workers similarly auto-connect to their cluster).
        from ray_tpu.config import cfg

        addr = cfg.head_address or None
        if addr:
            from ray_tpu.cluster.client import RemoteRuntime

            with _runtime_lock:
                if _runtime is None:
                    _runtime = RemoteRuntime(addr)
            return _runtime
        raise RuntimeError("ray_tpu.init() has not been called")
    return _runtime


def set_runtime(rt: Optional[Runtime]) -> None:
    global _runtime
    with _runtime_lock:
        _runtime = rt


def runtime_initialized() -> bool:
    return _runtime is not None


# ActorState lives in actor.py; imported late to avoid a cycle.
from .actor import ActorState  # noqa: E402,F401  (re-export for runtime users)
