"""Actors: stateful workers with ordered method execution and restarts.

Analog of the reference's actor stack (GcsActorManager state machine +
ActorTaskSubmitter ordered queues + TaskReceiver concurrency groups,
/root/reference/src/ray/gcs/actor/, src/ray/core_worker/task_submission/
actor_task_submitter.cc). Creation is centrally scheduled through the same
batched kernels as tasks; each live actor owns a dedicated executor thread
(or pool, for max_concurrency>1) so method ordering matches the reference's
per-caller sequencing. ``max_restarts`` drives the restart state machine on
node death.
"""
from __future__ import annotations

import threading
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .object_store import ObjectRef, TaskError


class ActorUnavailableError(Exception):
    pass


def method(**options):
    """Decorator carrying per-method options (num_returns, ...) — parity
    with ray.method (python/ray/actor.py)."""

    def wrap(fn):
        fn._ray_tpu_method_options = options
        return fn

    return wrap


class ActorState:
    """Server side of one actor instance."""

    def __init__(
        self,
        runtime,
        actor_id: str,
        cls: type,
        ctor_args: tuple,
        ctor_kwargs: dict,
        resources: Dict[str, float],
        *,
        name: Optional[str] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
    ):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.ctor_args = ctor_args
        self.ctor_kwargs = ctor_kwargs
        self.resources = resources
        self.name = name
        self.max_restarts = max_restarts
        self.max_task_retries = max_task_retries
        self.max_concurrency = max_concurrency
        self.restarts_used = 0
        self.node_id: Optional[str] = None
        self.instance: Any = None
        self.alive = False
        self.dead_forever = False
        self.death_cause: Optional[str] = None
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._threads: List[threading.Thread] = []
        self._held_req = None  # (node, ResourceRequest) while alive

    # -- lifecycle ------------------------------------------------------
    def on_created(self, node_id: str, instance: Any, held_req) -> None:
        with self._cond:
            self.node_id = node_id
            self.instance = instance
            self.alive = True
            self._held_req = held_req
            self._threads = [
                threading.Thread(
                    target=self._run_loop,
                    name=f"actor-{self.actor_id[:6]}-{i}",
                    daemon=True,
                )
                for i in range(self.max_concurrency)
            ]
            for t in self._threads:
                t.start()
            self._cond.notify_all()

    def mark_died(self, restart: bool) -> None:
        with self._cond:
            was_alive = self.alive
            self.alive = False
            self.instance = None
            if restart and self.restarts_used < self.max_restarts:
                self.restarts_used += 1
                self._cond.notify_all()
                if was_alive:
                    self.runtime._resubmit_actor_creation(self)
                return
            self.dead_forever = True
            self.death_cause = "killed" if not restart else "node died"
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        from .runtime import ActorDiedError

        for call in pending:
            for ref in call["returns"]:
                self.runtime.store.seal(
                    ref,
                    ActorDiedError(
                        f"actor {self.name or self.actor_id} is dead"
                    ),
                    is_error=True,
                )

    def stop(self) -> None:
        self.mark_died(restart=False)

    # -- method invocation ---------------------------------------------
    def submit_method(
        self, method_name: str, args: tuple, kwargs: dict, returns: List[ObjectRef]
    ) -> None:
        from .runtime import ActorDiedError

        with self._cond:
            if self.dead_forever:
                for ref in returns:
                    self.runtime.store.seal(
                        ref,
                        ActorDiedError(
                            f"actor {self.name or self.actor_id} is dead"
                        ),
                        is_error=True,
                    )
                return
            self._queue.append(
                {
                    "method": method_name,
                    "args": args,
                    "kwargs": kwargs,
                    "returns": returns,
                    "attempt": 0,
                }
            )
            self._cond.notify()

    def _run_loop(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cond:
                while self.alive and not self._queue:
                    self._cond.wait(timeout=0.5)
                if not self.alive:
                    return
                if me not in self._threads:
                    return  # superseded by a restart generation
                call = self._queue.popleft()
                instance = self.instance
            self._execute_call(instance, call)

    def _execute_call(self, instance: Any, call: dict) -> None:
        from .runtime import get_context

        ctx = get_context()
        ctx.node_id = self.node_id
        ctx.actor_id = self.actor_id
        try:
            args, kwargs = self.runtime._resolve_args(call["args"], call["kwargs"])
            fn = getattr(instance, call["method"])
            result = fn(*args, **kwargs)
            refs = call["returns"]
            values = [result] if len(refs) == 1 else tuple(result)
            node = self.runtime.nodes.get(self.node_id)
            for ref, value in zip(refs, values):
                if node is not None:
                    node.objects.add(ref.hex)
                self.runtime.store.seal(ref, value)
            self.runtime.metrics["tasks_finished"] += 1
        except BaseException as exc:  # noqa: BLE001
            if call["attempt"] < self.max_task_retries:
                call["attempt"] += 1
                with self._cond:
                    self._queue.appendleft(call)
                    self._cond.notify()
                return
            err = TaskError(exc, f"{self.cls.__name__}.{call['method']}")
            err.__cause__ = exc
            for ref in call["returns"]:
                self.runtime.store.seal(ref, err, is_error=True)
            self.runtime.metrics["tasks_failed"] += 1
        finally:
            ctx.node_id = None
            ctx.actor_id = None

    def requeue_front(self, call: dict) -> None:
        with self._cond:
            self._queue.appendleft(call)
            self._cond.notify()


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs, self._num_returns)

    def options(self, num_returns: Optional[int] = None, **_ignored):
        return ActorMethod(
            self._handle, self._name, num_returns or self._num_returns
        )


class ActorHandle:
    """Client-side handle (reference: python/ray/actor.py ActorHandle)."""

    def __init__(self, runtime, actor_id: str, cls: type):
        self._runtime = runtime
        self._actor_id = actor_id
        self._cls = cls

    @property
    def _actor_state(self) -> ActorState:
        return self._runtime._actors[self._actor_id]

    def __getattr__(self, name: str) -> ActorMethod:
        # dunders (except __call__, used by serve replicas) stay normal
        # attribute errors so pickling/copy protocols don't get hijacked
        if name.startswith("__") and name != "__call__":
            raise AttributeError(name)
        fn = getattr(self._cls, name, None)
        if fn is None or not callable(fn):
            raise AttributeError(
                f"actor class {self._cls.__name__} has no method {name!r}"
            )
        opts = getattr(fn, "_ray_tpu_method_options", {})
        return ActorMethod(self, name, opts.get("num_returns", 1))

    def _invoke(self, method_name, args, kwargs, num_returns):
        refs = [ObjectRef.new(owner=self._actor_id) for _ in range(num_returns)]
        for r in refs:
            self._runtime.store.create(r)
        self._runtime.metrics["tasks_submitted"] += 1
        self._actor_state.submit_method(method_name, args, kwargs, refs)
        return refs[0] if num_returns == 1 else refs

    def __repr__(self) -> str:
        return f"ActorHandle({self._cls.__name__}, {self._actor_id[:8]})"


def create_actor(
    runtime,
    cls: type,
    args: tuple,
    kwargs: dict,
    *,
    resources: Dict[str, float],
    name: Optional[str] = None,
    lifetime: Optional[str] = None,
    max_restarts: int = 0,
    max_task_retries: int = 0,
    max_concurrency: int = 1,
    scheduling_strategy=None,
) -> ActorHandle:
    """Create + centrally schedule an actor (GcsActorScheduler analog)."""
    if name is not None and name in runtime._named_actors:
        raise ValueError(f"actor name {name!r} already taken")
    actor_id = uuid.uuid4().hex[:16]
    state = ActorState(
        runtime,
        actor_id,
        cls,
        args,
        kwargs,
        resources,
        name=name,
        max_restarts=max_restarts,
        max_task_retries=max_task_retries,
        max_concurrency=max_concurrency,
    )
    runtime._actors[actor_id] = state
    if name is not None:
        runtime._named_actors[name] = actor_id
    runtime._submit_actor_creation(state, scheduling_strategy)
    return ActorHandle(runtime, actor_id, cls)
