"""Actors: stateful workers with ordered method execution and restarts.

Analog of the reference's actor stack (GcsActorManager state machine +
ActorTaskSubmitter ordered queues + TaskReceiver concurrency groups,
/root/reference/src/ray/gcs/actor/, src/ray/core_worker/task_submission/
actor_task_submitter.cc, task_execution/concurrency_group_manager.h).
Creation is centrally scheduled through the same batched kernels as tasks.

Execution model (reference parity):

- **Sync actors**: per-concurrency-group FIFO queues drained by
  ``max_concurrency`` threads per group (default group = 1 thread → strict
  method ordering, like the reference's ordered execution queue).
- **Async actors** (any ``async def`` method): ALL methods multiplex on one
  asyncio event loop owned by the actor (the reference's fiber/asyncio
  mode, core_worker/task_execution/fiber.h); per-group
  ``asyncio.Semaphore``s bound in-flight starts, default 1000 like
  ray_constants DEFAULT_MAX_CONCURRENCY_ASYNC.
- ``concurrency_groups={"io": 2, ...}`` on the class plus
  ``@method(concurrency_group="io")`` route methods to dedicated
  groups so one group saturating can't starve another.

``max_restarts`` drives the restart state machine on node death.
"""
from __future__ import annotations

import asyncio
import inspect
import threading
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .object_store import ObjectRef, TaskError

DEFAULT_MAX_CONCURRENCY_ASYNC = 1000


def _coroutine_method_names(cls: type) -> set:
    names = set()
    for klass in cls.__mro__:
        for name, val in vars(klass).items():
            if inspect.iscoroutinefunction(val):
                names.add(name)
    return names


class ActorUnavailableError(Exception):
    pass


def method(**options):
    """Decorator carrying per-method options (num_returns, ...) — parity
    with ray.method (python/ray/actor.py)."""

    def wrap(fn):
        fn._ray_tpu_method_options = options
        return fn

    return wrap


class ActorState:
    """Server side of one actor instance."""

    def __init__(
        self,
        runtime,
        actor_id: str,
        cls: type,
        ctor_args: tuple,
        ctor_kwargs: dict,
        resources: Dict[str, float],
        *,
        name: Optional[str] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: Optional[int] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
    ):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.ctor_args = ctor_args
        self.ctor_kwargs = ctor_kwargs
        self.resources = resources
        self.name = name
        self.max_restarts = max_restarts
        self.max_task_retries = max_task_retries
        self.is_async = bool(_coroutine_method_names(cls))
        if max_concurrency is None:
            # reference defaults: 1000 for asyncio actors, 1 for threaded
            # (an EXPLICIT max_concurrency=1 on an async actor is honored —
            # it serializes method execution)
            max_concurrency = (
                DEFAULT_MAX_CONCURRENCY_ASYNC if self.is_async else 1
            )
        self.max_concurrency = max_concurrency
        self.concurrency_groups = dict(concurrency_groups or {})
        self.restarts_used = 0
        self.node_id: Optional[str] = None
        self.instance: Any = None
        self.alive = False
        self.dead_forever = False
        self.death_cause: Optional[str] = None
        # sync mode: one FIFO per concurrency group; async mode: one event
        # loop + per-group semaphores. "_default" always exists.
        self._group_limits = {"_default": self.max_concurrency}
        self._group_limits.update(self.concurrency_groups)
        self._queues: Dict[str, deque] = {
            g: deque() for g in self._group_limits
        }
        self._cond = threading.Condition()
        self._threads: List[threading.Thread] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._semaphores: Dict[str, asyncio.Semaphore] = {}
        # async calls started but not yet sealed, keyed by id(call) — the
        # death path and the completion callback race to seal; whoever pops
        # the entry first does it
        self._inflight: Dict[int, dict] = {}
        self._held_req = None  # (node, ResourceRequest) while alive
        # set lazily when a compiled DAG binds this actor: serializes DAG
        # stage calls against normal .remote() method execution
        self.dag_lock: Optional[threading.Lock] = None

    # -- lifecycle ------------------------------------------------------
    def on_created(self, node_id: str, instance: Any, held_req) -> None:
        with self._cond:
            self.node_id = node_id
            self.instance = instance
            self.alive = True
            self._held_req = held_req
            if self.is_async:
                self._start_event_loop()
                # redeliver calls queued while dead/restarting
                for q in self._queues.values():
                    while q:
                        self._dispatch_async(q.popleft())
            else:
                self._threads = [
                    threading.Thread(
                        target=self._run_loop,
                        args=(group,),
                        name=f"actor-{self.actor_id[:6]}-{group}-{i}",
                        daemon=True,
                    )
                    for group, limit in self._group_limits.items()
                    for i in range(max(1, int(limit)))
                ]
                for t in self._threads:
                    t.start()
            self._cond.notify_all()

    def _start_event_loop(self) -> None:
        loop = asyncio.new_event_loop()
        ready = threading.Event()

        def run() -> None:
            asyncio.set_event_loop(loop)
            # semaphores must bind to this loop
            self._semaphores = {
                g: asyncio.Semaphore(max(1, int(limit)))
                for g, limit in self._group_limits.items()
            }
            ready.set()
            loop.run_forever()

        self._loop = loop
        self._loop_thread = threading.Thread(
            target=run, name=f"actor-{self.actor_id[:6]}-loop", daemon=True
        )
        self._loop_thread.start()
        ready.wait()

    def mark_died(self, restart: bool) -> None:
        dropped: List[dict] = []
        restarting = False
        with self._cond:
            was_alive = self.alive
            self.alive = False
            self.instance = None
            self._stop_event_loop()
            if restart and self.restarts_used < self.max_restarts:
                restarting = True
                self.restarts_used += 1
                # in-flight calls died with the instance: retry-eligible ones
                # requeue for redelivery after restart, the rest fail now
                # (reference: actor task retries, max_task_retries)
                for call in self._inflight.values():
                    if call["attempt"] < self.max_task_retries:
                        call["attempt"] += 1
                        self._queues[call["group"]].append(call)
                    else:
                        dropped.append(call)
                self._inflight.clear()
                self._cond.notify_all()
            else:
                self.dead_forever = True
                self.death_cause = "killed" if not restart else "node died"
                dropped = [c for q in self._queues.values() for c in q]
                for q in self._queues.values():
                    q.clear()
                dropped.extend(self._inflight.values())
                self._inflight.clear()
                self._cond.notify_all()
        if restarting and was_alive:
            self.runtime._resubmit_actor_creation(self)
        self._seal_dead(
            dropped,
            "restarted mid-call" if restarting else "is dead",
        )

    def _seal_dead(self, calls: List[dict], why: str) -> None:
        from .runtime import ActorDiedError

        for call in calls:
            err = ActorDiedError(f"actor {self.name or self.actor_id} {why}")
            if call.get("stream_tid"):
                # a queued streaming call dies with the actor: end the
                # stream with the error as its final item
                self.runtime._fail_stream(call["stream_tid"], err)
                continue
            for ref in call["returns"]:
                self.runtime.store.seal(ref, err, is_error=True)

    def _stop_event_loop(self) -> None:
        loop = self._loop
        if loop is not None:
            self._loop = None
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass

    def stop(self) -> None:
        self.mark_died(restart=False)

    # -- method invocation ---------------------------------------------
    def submit_method(
        self,
        method_name: str,
        args: tuple,
        kwargs: dict,
        returns: List[ObjectRef],
        stream_tid: Optional[str] = None,
    ) -> None:
        from .runtime import ActorDiedError

        with self._cond:
            if self.dead_forever:
                err = ActorDiedError(
                    f"actor {self.name or self.actor_id} is dead"
                )
                if stream_tid is not None:
                    self.runtime._fail_stream(stream_tid, err)
                for ref in returns:
                    self.runtime.store.seal(ref, err, is_error=True)
                return
            group = self._method_group(method_name)
            call = {
                "method": method_name,
                "args": args,
                "kwargs": kwargs,
                "returns": returns,
                "attempt": 0,
                "group": group,
                "stream_tid": stream_tid,
            }
            if self.is_async and self.alive:
                self._dispatch_async(call)
                return
            self._queues[group].append(call)
            self._cond.notify_all()

    def _method_group(self, method_name: str) -> str:
        fn = getattr(self.cls, method_name, None)
        opts = getattr(fn, "_ray_tpu_method_options", None) or {}
        group = opts.get("concurrency_group", "_default")
        return group if group in self._group_limits else "_default"

    def _run_loop(self, group: str) -> None:
        me = threading.current_thread()
        queue = self._queues[group]
        while True:
            with self._cond:
                while self.alive and not queue:
                    self._cond.wait(timeout=0.5)
                if not self.alive:
                    return
                if me not in self._threads:
                    return  # superseded by a restart generation
                call = queue.popleft()
                instance = self.instance
            self._execute_call(instance, call)

    # -- async execution (asyncio actor mode) ---------------------------
    def _dispatch_async(self, call: dict) -> None:
        """Schedule one method call on the actor's event loop. Caller holds
        self._cond. In-flight starts are bounded per concurrency group by a
        semaphore (reference: max_concurrency / max_concurrency_per_group)."""
        loop = self._loop
        instance = self.instance
        self._inflight[id(call)] = call

        async def run() -> None:
            async with self._semaphores[call["group"]]:
                await self._execute_call_async(instance, call)

        # cheaper than run_coroutine_threadsafe: no wrapping future — the
        # coroutine seals its own refs, nothing awaits the task handle
        loop.call_soon_threadsafe(loop.create_task, run())

    async def _execute_call_async(self, instance: Any, call: dict) -> None:
        from .runtime import get_context

        ctx = get_context()
        ctx.node_id = self.node_id
        ctx.actor_id = self.actor_id
        try:
            args, kwargs = self.runtime._resolve_args(call["args"], call["kwargs"])
            fn = getattr(instance, call["method"])
            result = fn(*args, **kwargs)
            from .object_store import should_await

            if should_await(result):
                result = await result
            self._seal_result(call, result)
        except BaseException as exc:  # noqa: BLE001
            self._seal_failure(call, exc)
        finally:
            ctx.node_id = None
            ctx.actor_id = None

    def _execute_call(self, instance: Any, call: dict) -> None:
        from .runtime import get_context

        ctx = get_context()
        ctx.node_id = self.node_id
        ctx.actor_id = self.actor_id
        try:
            if call.get("stream_tid"):
                # num_returns="streaming" method: the generator drives the
                # runtime's per-item stream machinery; ANY failure —
                # argument resolution included — seals as the final
                # stream item (no per-call retries: a resumed generator
                # cannot replay consumed yields). The dag_lock spans the
                # WHOLE drive: a generator function body runs lazily, so
                # locking only its creation would serialize nothing.
                tid = call["stream_tid"]
                import contextlib

                try:
                    args, kwargs = self.runtime._resolve_args(
                        call["args"], call["kwargs"]
                    )
                    fn = getattr(instance, call["method"])
                    guard = (
                        self.dag_lock
                        if self.dag_lock is not None
                        else contextlib.nullcontext()
                    )
                    with guard:
                        gen = fn(*args, **kwargs)
                        self.runtime.run_actor_stream(
                            tid, self.node_id, gen
                        )
                    self.runtime.metrics["tasks_finished"] += 1
                except BaseException as exc:  # noqa: BLE001
                    err = TaskError(
                        exc, f"{self.cls.__name__}.{call['method']}"
                    )
                    err.__cause__ = exc
                    self.runtime._fail_stream(tid, err)
                    self.runtime.metrics["tasks_failed"] += 1
                return
            args, kwargs = self.runtime._resolve_args(call["args"], call["kwargs"])
            fn = getattr(instance, call["method"])
            lock = self.dag_lock
            if lock is not None:
                with lock:
                    result = fn(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            self._seal_result(call, result)
        except BaseException as exc:  # noqa: BLE001
            self._seal_failure(call, exc)
        finally:
            ctx.node_id = None
            ctx.actor_id = None

    def _take_ownership(self, call: dict) -> bool:
        """Async mode: completion and the death path race to seal the same
        refs; whoever pops the in-flight entry owns them."""
        if not self.is_async:
            return True
        with self._cond:
            return self._inflight.pop(id(call), None) is not None

    def _seal_result(self, call: dict, result: Any) -> None:
        if not self._take_ownership(call):
            return
        refs = call["returns"]
        values = [result] if len(refs) == 1 else tuple(result)
        node = self.runtime.nodes.get(self.node_id)
        for ref, value in zip(refs, values):
            if node is not None:
                node.objects.add(ref.hex)
            self.runtime.store.seal(ref, value)
        self.runtime.metrics["tasks_finished"] += 1

    def _seal_failure(self, call: dict, exc: BaseException) -> None:
        if not self._take_ownership(call):
            return
        if call["attempt"] < self.max_task_retries:
            requeued = False
            with self._cond:
                # a concurrent kill may have drained-and-sealed the queues
                # already; retrying onto a dead queue would strand the refs
                if not self.dead_forever:
                    call["attempt"] += 1
                    if self.is_async and self.alive and self._loop is not None:
                        self._dispatch_async(call)
                    else:
                        self._queues[call["group"]].appendleft(call)
                        self._cond.notify_all()
                    requeued = True
            if requeued:
                return
        err = TaskError(exc, f"{self.cls.__name__}.{call['method']}")
        err.__cause__ = exc
        for ref in call["returns"]:
            self.runtime.store.seal(ref, err, is_error=True)
        self.runtime.metrics["tasks_failed"] += 1


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs, self._num_returns)

    def options(self, num_returns: Optional[int] = None, **_ignored):
        return ActorMethod(
            self._handle, self._name, num_returns or self._num_returns
        )


class ActorHandle:
    """Client-side handle (reference: python/ray/actor.py ActorHandle)."""

    def __init__(self, runtime, actor_id: str, cls: type):
        self._runtime = runtime
        self._actor_id = actor_id
        self._cls = cls
        # per-name ActorMethod memo: a.f.remote() in a hot loop resolves
        # the class attribute + options once instead of per call
        self._methods: Dict[str, ActorMethod] = {}

    @property
    def _actor_state(self) -> ActorState:
        return self._runtime._actors[self._actor_id]

    def __getattr__(self, name: str) -> ActorMethod:
        # dunders (except __call__, used by serve replicas) stay normal
        # attribute errors so pickling/copy protocols don't get hijacked
        if name.startswith("__") and name != "__call__":
            raise AttributeError(name)
        # __dict__ access (not attribute access): an instance materialized
        # without __init__ (copy/unpickle protocols) must not recurse here
        methods = self.__dict__.get("_methods")
        if methods is not None:
            cached = methods.get(name)
            if cached is not None:
                return cached
        fn = getattr(self._cls, name, None)
        if fn is None or not callable(fn):
            raise AttributeError(
                f"actor class {self._cls.__name__} has no method {name!r}"
            )
        opts = getattr(fn, "_ray_tpu_method_options", {})
        m = ActorMethod(self, name, opts.get("num_returns", 1))
        if methods is not None:
            methods[name] = m
        return m

    def _invoke(self, method_name, args, kwargs, num_returns):
        if num_returns == "streaming":
            from ray_tpu.cluster.common import new_id
            from .object_store import ObjectRefGenerator

            state = self._actor_state
            target = getattr(state.cls, method_name, None)
            if (
                state.is_async
                or inspect.iscoroutinefunction(target)
                or inspect.isasyncgenfunction(target)
            ):
                raise TypeError(
                    "num_returns='streaming' is not supported on async "
                    "actors; use a sync actor or a task"
                )
            tid = new_id()
            # state exists from submission so an abandon arriving before
            # the executor starts sticks (runtime.register_stream)
            self._runtime.register_stream(tid)
            self._runtime.metrics["tasks_submitted"] += 1
            state.submit_method(
                method_name, args, kwargs, [], stream_tid=tid
            )
            return ObjectRefGenerator(tid, self._runtime)
        refs = [ObjectRef.new(owner=self._actor_id) for _ in range(num_returns)]
        for r in refs:
            self._runtime.store.create(r)
        self._runtime.metrics["tasks_submitted"] += 1
        self._actor_state.submit_method(method_name, args, kwargs, refs)
        return refs[0] if num_returns == 1 else refs

    def __repr__(self) -> str:
        return f"ActorHandle({self._cls.__name__}, {self._actor_id[:8]})"


def create_actor(
    runtime,
    cls: type,
    args: tuple,
    kwargs: dict,
    *,
    resources: Dict[str, float],
    name: Optional[str] = None,
    lifetime: Optional[str] = None,
    max_restarts: int = 0,
    max_task_retries: int = 0,
    max_concurrency: int = 1,
    concurrency_groups: Optional[Dict[str, int]] = None,
    scheduling_strategy=None,
) -> ActorHandle:
    """Create + centrally schedule an actor (GcsActorScheduler analog)."""
    if name is not None and name in runtime._named_actors:
        raise ValueError(f"actor name {name!r} already taken")
    actor_id = uuid.uuid4().hex[:16]
    state = ActorState(
        runtime,
        actor_id,
        cls,
        args,
        kwargs,
        resources,
        name=name,
        max_restarts=max_restarts,
        max_task_retries=max_task_retries,
        max_concurrency=max_concurrency,
        concurrency_groups=concurrency_groups,
    )
    runtime._actors[actor_id] = state
    if name is not None:
        runtime._named_actors[name] = actor_id
    runtime._submit_actor_creation(state, scheduling_strategy)
    return ActorHandle(runtime, actor_id, cls)
