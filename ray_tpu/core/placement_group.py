"""Placement groups: gang resource reservation via the TPU bin-pack kernels.

Analog of the reference PG stack (GcsPlacementGroupManager/Scheduler +
bundle policies + 2-phase commit, /root/reference/src/ray/gcs/
gcs_placement_group_scheduler.cc:41-219 and python/ray/util/
placement_group.py). Bundle placement runs through
``ray_tpu.scheduler.schedule_bundles`` (the batched PACK/SPREAD/STRICT_*
kernels); the chosen layout is then committed two-phase against each node's
exact ledger — all bundles allocate or the whole reservation rolls back and
the PG is retried when cluster resources change.
"""
from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.scheduler import ResourceRequest, schedule_bundles
from .object_store import ObjectRef


@dataclass
class _Bundle:
    request: ResourceRequest
    node_id: Optional[str] = None
    avail_fp: Optional[Dict[int, int]] = None  # remaining capacity inside bundle


class PlacementGroupState:
    """Head-side PG record + per-bundle reserved-resource ledgers."""

    def __init__(self, runtime, bundles: List[Dict[str, float]], strategy: str,
                 name: str = "", avoid_nodes: Optional[List[str]] = None):
        self.runtime = runtime
        self.id = uuid.uuid4().hex[:16]
        self.name = name
        self.strategy = strategy
        self.avoid_nodes = [str(n) for n in (avoid_nodes or ())]
        self.bundle_specs = [dict(b) for b in bundles]
        self.bundles = [
            _Bundle(ResourceRequest.from_map(runtime.vocab, b)) for b in bundles
        ]
        self.ready_event = threading.Event()
        self.ready_ref = ObjectRef.new(owner="pg")
        runtime.store.create(self.ready_ref)
        self._lock = threading.Lock()
        self.removed = False
        # dense demand matrix memo: a pending PG retries try_schedule on
        # every view change — restacking the (immutable) bundle demands
        # per attempt was pure overhead (keyed by view width, which can
        # grow when new resource names appear)
        self._dense: Optional[tuple] = None  # (width, np.ndarray)

    # -- scheduling (called from the scheduler thread) ------------------
    def try_schedule(self) -> bool:
        """Run the bundle kernel + 2PC commit. True if now ready."""
        rt = self.runtime
        totals, avail, alive = rt.view.active_arrays()
        if rt.view.num_nodes == 0:
            return False
        width = totals.shape[1]
        if self._dense is None or self._dense[0] != width:
            self._dense = (
                width,
                np.stack([b.request.dense(width) for b in self.bundles]),
            )
        mat = self._dense[1]
        if self.avoid_nodes:
            from ray_tpu.scheduler.bundles import (
                schedule_bundles_soft_avoid,
            )

            nodes_idx, success, _ = schedule_bundles_soft_avoid(
                totals, avail, alive, mat, self.strategy,
                [rt.view.row_if_known(n) for n in self.avoid_nodes],
            )
        else:
            nodes_idx, success, _ = schedule_bundles(
                totals, avail, alive, mat, strategy=self.strategy
            )
        if not success:
            return False
        chosen = [rt.view.node_id(int(r)) for r in nodes_idx]
        # Phase 1: prepare — allocate on every node ledger, rollback on any
        # failure (PrepareBundleResources, gcs_placement_group_scheduler.cc:192).
        done: List[int] = []
        for i, node_id in enumerate(chosen):
            node = rt.nodes.get(node_id)
            if node is None or not node.alive or not node.ledger.try_allocate(
                self.bundles[i].request
            ):
                for j in done:
                    rt.nodes[chosen[j]].ledger.release(self.bundles[j].request)
                return False
            done.append(i)
        # Phase 2: commit.
        for i, node_id in enumerate(chosen):
            b = self.bundles[i]
            b.node_id = node_id
            b.avail_fp = dict(b.request.demands)
            rt.view.update_available(node_id, rt.nodes[node_id].ledger.avail_map())
        self.ready_event.set()
        rt.store.seal(self.ready_ref, True)
        return True

    # -- bundle-resource accounting ------------------------------------
    def pick_bundle(self, bundle_index: int, req: ResourceRequest):
        """Choose a bundle that can host ``req``. Returns (node_id, idx) or
        None."""
        with self._lock:
            if not self.ready_event.is_set() or self.removed:
                return None
            candidates = (
                range(len(self.bundles))
                if bundle_index < 0
                else [bundle_index]
            )
            for i in candidates:
                b = self.bundles[i]
                if all(
                    b.avail_fp.get(c, 0) >= q for c, q in req.demands.items()
                ):
                    return b.node_id, i
            return None

    def try_allocate(self, bundle_index: int, req: ResourceRequest) -> bool:
        with self._lock:
            b = self.bundles[bundle_index]
            if b.avail_fp is None or any(
                b.avail_fp.get(c, 0) < q for c, q in req.demands.items()
            ):
                return False
            for c, q in req.demands.items():
                b.avail_fp[c] -= q
            return True

    def release(self, bundle_index: int, req: ResourceRequest) -> None:
        with self._lock:
            b = self.bundles[bundle_index]
            if b.avail_fp is None:
                return
            for c, q in req.demands.items():
                b.avail_fp[c] = b.avail_fp.get(c, 0) + q

    def remove(self) -> None:
        with self._lock:
            if self.removed:
                return
            self.removed = True
            if self.ready_event.is_set():
                for b in self.bundles:
                    node = self.runtime.nodes.get(b.node_id)
                    if node is not None and node.alive:
                        node.ledger.release(b.request)
                        self.runtime.view.update_available(
                            b.node_id, node.ledger.avail_map()
                        )


class PlacementGroup:
    """User-facing handle (reference: python/ray/util/placement_group.py)."""

    def __init__(self, state: PlacementGroupState):
        self._state = state

    @property
    def id(self) -> str:
        return self._state.id

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self._state.bundle_specs

    def ready(self) -> ObjectRef:
        return self._state.ready_ref

    def wait(self, timeout_seconds: float = 30) -> bool:
        return self._state.ready_event.wait(timeout_seconds)

    def __repr__(self) -> str:
        return f"PlacementGroup({self.id[:8]}, {self._state.strategy})"


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    avoid_nodes: Optional[List[str]] = None,
):
    """``avoid_nodes`` is a SOFT anti-affinity list (gang-aware reshape
    placement): the bundle kernels first run with those nodes masked out
    and fall back to the full cluster when the masked placement is
    infeasible — an elastic gang avoiding a flapping node must never
    park behind the preference."""
    from .runtime import get_runtime

    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        from ray_tpu.cluster.client import RemotePlacementGroup

        pg_id = rt.create_placement_group(
            list(bundles), strategy, avoid_nodes=avoid_nodes
        )
        return RemotePlacementGroup(pg_id, list(bundles), strategy)
    state = PlacementGroupState(
        rt, bundles, strategy, name=name, avoid_nodes=avoid_nodes
    )
    rt.register_pg(state)
    return PlacementGroup(state)


def remove_placement_group(pg) -> None:
    from .runtime import get_runtime

    rt = get_runtime()
    if getattr(rt, "is_remote", False):
        rt.remove_placement_group(pg.id)
        return
    pg._state.remove()
    rt._pgs.pop(pg.id, None)
    rt.notify_resources_changed()


def placement_group_table() -> Dict[str, dict]:
    from .runtime import get_runtime

    rt = get_runtime()
    out = {}
    for pg_id, st in rt._pgs.items():
        out[pg_id] = {
            "placement_group_id": pg_id,
            "name": st.name,
            "strategy": st.strategy,
            "state": "REMOVED"
            if st.removed
            else ("CREATED" if st.ready_event.is_set() else "PENDING"),
            "bundles": {
                i: {"node_id": b.node_id, "resources": st.bundle_specs[i]}
                for i, b in enumerate(st.bundles)
            },
        }
    return out
