"""Core runtime: tasks, actors, objects, placement groups, lease scheduling."""
