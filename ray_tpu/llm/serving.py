"""LLM serving: engine replicas behind ray_tpu.serve.

Analog of the reference's serve-side LLM deployments (/root/reference/
python/ray/llm/_internal/serve/): build_llm_deployment returns a Serve
application whose replicas each hold an engine; requests are
{"prompt": str, "max_new_tokens"?: int, "temperature"?: float}.
"""
from __future__ import annotations

from typing import Any, Optional

import ray_tpu.serve as serve
from .engine import GenerationConfig, LLMEngine


def build_llm_deployment(
    model_config: Any,
    params: Optional[Any] = None,
    *,
    name: str = "llm",
    num_replicas: int = 1,
    max_len: int = 256,
    engine: str = "dense",  # "dense" | "continuous" (paged KV)
    max_batch: int = 8,
    page_size: int = 16,
    n_pages: int = 256,
):
    if engine not in ("dense", "continuous"):
        raise ValueError(
            f"unknown engine {engine!r}; expected 'dense' or 'continuous'"
        )

    @serve.deployment(name=name, num_replicas=num_replicas)
    class LLMServer:
        def __init__(self):
            if engine == "continuous":
                from .continuous import ContinuousBatchingEngine

                self.engine = ContinuousBatchingEngine(
                    model_config,
                    params,
                    max_batch=max_batch,
                    page_size=page_size,
                    n_pages=n_pages,
                )
            else:
                self.engine = LLMEngine(model_config, params, max_len=max_len)

        def __call__(self, request):
            prompt = request["prompt"]
            gen = GenerationConfig(
                max_new_tokens=int(request.get("max_new_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                seed=int(request.get("seed", 0)),
            )
            text = self.engine.generate([prompt], gen)[0]
            return {"prompt": prompt, "generated_text": text}

        def stream_tokens(self, request):
            """Generator-based token streaming: call with
            ``.options(num_returns="streaming")`` and iterate the
            ObjectRefGenerator — each decoded token text seals as its own
            object with normal object-plane semantics (the reference's
            serve/LLM token streaming rides ObjectRefGenerator the same
            way; the Channel path below is the lower-latency in-cluster
            alternative)."""
            if not hasattr(self.engine, "stream_ids"):
                raise TypeError(
                    "token streaming requires engine='continuous'"
                )
            gen = GenerationConfig(
                max_new_tokens=int(request.get("max_new_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                seed=int(request.get("seed", 0)),
            )
            prompt = self.engine.tokenizer.encode(request["prompt"])
            for tok in self.engine.stream_ids(prompt, gen):
                yield self.engine.tokenizer.decode([int(tok)])

        def stream_to(self, writer, request):
            """HTTP proxy SSE contract: POST /<name>/stream streams decoded
            token text through a mutable-object Channel (continuous engine
            only — the dense engine decodes whole batches)."""
            if not hasattr(self.engine, "stream_ids"):
                writer.write("streaming requires engine='continuous'")
                writer.close_channel()
                return 0
            gen = GenerationConfig(
                max_new_tokens=int(request.get("max_new_tokens", 32)),
                temperature=float(request.get("temperature", 0.0)),
                seed=int(request.get("seed", 0)),
            )
            prompt = self.engine.tokenizer.encode(request["prompt"])
            n = 0
            for tok in self.engine.stream_ids(prompt, gen):
                writer.write(self.engine.tokenizer.decode([int(tok)]))
                n += 1
            writer.close_channel()
            return n

    return LLMServer.bind()
