"""LLM serving: engine replicas behind ray_tpu.serve.

Analog of the reference's serve-side LLM deployments (/root/reference/
python/ray/llm/_internal/serve/): build_llm_deployment returns a Serve
application whose replicas each hold an engine; requests are
{"prompt": str, "max_new_tokens"?: int, "temperature"?: float}.

Serving-plane integration (PR 8):

- replicas of a ``continuous`` deployment share prefilled KV through
  the node's shm arena (:mod:`ray_tpu.serve.prefix_cache`) — a repeated
  prompt prefix is a pinned read-only view copy-in, not a prefill;
- streams are **resumable**: generation is per-request deterministic
  (seeded), so ``stream_to`` honors ``resume_from=n`` by regenerating
  and skipping the first ``n`` tokens — the router uses this to fail a
  stream over to another replica mid-flight with no duplicated or lost
  acked tokens. Caveat: exactness assumes the resumed replica computes
  the same logits as the original. The cache-hit suffix-prefill kernel
  and the full-prefill kernel differ in reduction shape, so their
  logits can differ in the last ulps; if the original and failover
  replicas take DIFFERENT prefill paths AND a sampled/argmaxed token
  sits within float epsilon of a tie, the resumed trajectory can
  diverge. Real models' logit gaps dwarf that epsilon (the chaos
  suite's token-exact invariant has never tripped on it), but the
  guarantee is probabilistic at the ulp level, not bitwise;
- replicas report engine + prefix-cache stats to their node agent
  (DebugState ``serve`` block) and expose ``serve_stats`` to the
  router's head reporter (QueryState("serve")).
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
import ray_tpu.serve as serve
from .engine import GenerationConfig, LLMEngine


def _params_sig(model_config: Any, params: Optional[Any], name: str) -> str:
    """Cheap weight signature for the shared prefix cache: KV computed
    under different weights must never collide. Hashes the config repr
    plus a slice of the first parameter leaf (or the default-init
    marker when params is None)."""
    h = hashlib.sha256(f"{name}:{model_config}".encode())
    if params is None:
        h.update(b"default-init-seed0")
    else:
        import jax
        import numpy as np

        leaves = jax.tree_util.tree_leaves(params)
        h.update(str(len(leaves)).encode())
        if leaves:
            first = np.asarray(leaves[0]).ravel()[:256]
            h.update(first.tobytes())
            h.update(str(np.asarray(leaves[0]).shape).encode())
    return h.hexdigest()[:24]


def _gen_from_request(request) -> GenerationConfig:
    return GenerationConfig(
        max_new_tokens=int(request.get("max_new_tokens", 32)),
        temperature=float(request.get("temperature", 0.0)),
        seed=int(request.get("seed", 0)),
    )


def build_llm_deployment(
    model_config: Any,
    params: Optional[Any] = None,
    *,
    name: str = "llm",
    num_replicas: int = 1,
    max_len: int = 256,
    engine: str = "dense",  # "dense" | "continuous" (paged KV)
    max_batch: int = 8,
    page_size: int = 16,
    n_pages: int = 256,
    prefix_cache: bool = True,
    slo: Optional[Any] = None,
    # disaggregated serving (PR 18): >0 stands up a companion
    # "<name>-prefill" deployment — the router runs the prefill phase
    # there, KV pages ship to these (now decode-only) replicas as
    # sealed device frames, and decode scales independently
    prefill_replicas: int = 0,
    # model multiplexing: extra weight pytrees replicas hot-swap
    # between ({model_id: params}); the base weights are model id
    # ``base_model_id``
    variants: Optional[Dict[str, Any]] = None,
    base_model_id: str = "base",
):
    if engine not in ("dense", "continuous"):
        raise ValueError(
            f"unknown engine {engine!r}; expected 'dense' or 'continuous'"
        )
    if (prefill_replicas or variants) and engine != "continuous":
        raise ValueError(
            "prefill/decode disaggregation and model multiplexing "
            "require engine='continuous' (paged KV)"
        )
    model_sig = _params_sig(model_config, params, name)
    models = (
        [base_model_id, *variants] if variants else None
    )

    def _make_engine(model_id: str):
        from .continuous import ContinuousBatchingEngine

        cache = None
        if prefix_cache:
            from ray_tpu.serve.prefix_cache import cache_from_cfg

            cache = cache_from_cfg(
                page_size=page_size, model_sig=model_sig
            )
        return ContinuousBatchingEngine(
            model_config,
            params,
            max_batch=max_batch,
            page_size=page_size,
            n_pages=n_pages,
            prefix_cache=cache,
            model_id=model_id,
        )

    prefill_dep_name = f"{name}-prefill" if prefill_replicas else None

    @serve.deployment(
        name=name,
        num_replicas=num_replicas,
        # continuous-engine generation is per-request deterministic
        # (seeded sampling), so streams can fail over mid-flight
        resumable_streams=(engine == "continuous"),
        stats_method="serve_stats",
        slo=slo,
        prefill_deployment=prefill_dep_name,
        models=models,
    )
    class LLMServer:
        def __init__(self):
            if engine == "continuous":
                self.engine = _make_engine(base_model_id)
            else:
                self.engine = LLMEngine(model_config, params, max_len=max_len)
            self._tokens_out = 0
            # hot-swap plane: base + variant weights by model id; the
            # node WeightsHub (shm arena) is probed first so same-node
            # siblings pull sealed device frames instead of re-reading
            # the closure capture
            self._variants = dict(variants or {})
            self._variants[base_model_id] = getattr(
                self.engine, "params", params
            )
            self._hub = None
            if variants:
                from ray_tpu.serve.model_store import hub_from_node

                self._hub = hub_from_node(name)
            self._swap_lock = threading.Lock()
            self._swap_done_t: Optional[float] = None
            self._swaps = 0
            self._ft_new_count = 0
            self._ft_new_ms_sum = 0.0
            # KV handoff accounting (disagg bench kv_handoff_mb_per_s)
            self._handoff_bytes = 0
            self._handoff_s = 0.0
            self._handoffs = 0
            self._handoff_fallbacks = 0
            self._start_agent_reporter()

        # -- model multiplexing ------------------------------------------
        def _ensure_model(self, request) -> None:
            model = (
                request.get("model") if isinstance(request, dict) else None
            )
            if (
                model
                and hasattr(self.engine, "swap_params")
                and model != self.engine.model_id
            ):
                self.swap_weights({"model": model})

        def swap_weights(self, request) -> dict:
            """Admin/routing-triggered weights hot-swap: drain in-flight
            generation on the old weights-epoch, install the new model's
            params (WeightsHub device-frame pull when published, closure
            variant fallback), bump the epoch. Zero stream errors by
            construction — active slots finish before the swap lands."""
            from ray_tpu.serve import model_store as ms

            model = request["model"]
            version = int(request.get("version", 0))
            with self._swap_lock:
                if model == self.engine.model_id:
                    return {
                        "model": model,
                        "epoch": self.engine.weights_epoch,
                        "swapped": False,
                    }
                labels = {"deployment": name, "model": str(model)}
                t0 = time.monotonic()
                new_params = None
                if self._hub is not None:
                    new_params = self._hub.pull(model, version)
                if new_params is None:
                    if model not in self._variants:
                        ms.WEIGHT_SWAP_FAILURES.inc(labels=labels)
                        raise ValueError(
                            f"unknown model {model!r} for deployment "
                            f"{name!r} (known: {sorted(self._variants)})"
                        )
                    new_params = self._variants[model]
                    if self._hub is not None:
                        # publish for same-node siblings: their pull
                        # lands device frames straight from the arena
                        self._hub.publish(model, version, new_params)
                t_drain = time.monotonic()
                epoch = self.engine.swap_params(new_params, model_id=model)
                now = time.monotonic()
                ms.WEIGHT_SWAP_DRAIN_MS.observe(
                    (now - t_drain) * 1000.0, labels=labels
                )
                ms.WEIGHT_SWAP_MS.observe(
                    (now - t0) * 1000.0, labels=labels
                )
                ms.WEIGHT_SWAPS.inc(labels=labels)
                self._swap_done_t = now
                self._swaps += 1
                return {"model": model, "epoch": epoch, "swapped": True}

        def _note_first_token(self) -> None:
            """First token generated after a swap: export the
            first-token-on-new-weights latency exactly once."""
            if self._swap_done_t is None:
                return
            from ray_tpu.serve import model_store as ms

            t, self._swap_done_t = self._swap_done_t, None
            ft_ms = (time.monotonic() - t) * 1000.0
            ms.FIRST_TOKEN_NEW_WEIGHTS_MS.observe(
                ft_ms,
                labels={
                    "deployment": name,
                    "model": str(self.engine.model_id),
                },
            )
            # instance-level mirror of the histogram: metrics are
            # per-process, so the bench driver (another process) reads
            # these through serve_stats instead
            self._ft_new_count += 1
            self._ft_new_ms_sum += ft_ms

        # -- KV handoff (decode side) ------------------------------------
        def _adopt_handoff(self, handoff) -> Optional[int]:
            """Pull the prefill worker's sealed KV pages over the data
            plane (device landing when the plane is on) and graft them
            into the engine. Returns the adopted req_id, or None on ANY
            failure — prefill death mid-handoff, model mismatch, pool
            backpressure — in which case the caller re-prefills locally
            (token-exact: generation is seed-deterministic)."""
            from ray_tpu.cluster import device_plane as _dp

            t0 = time.monotonic()
            try:
                ref = handoff[0]
                if _dp.device_plane_enabled():
                    with _dp.landing("device"):
                        manifest, k, v = ray_tpu.get(ref, timeout=30.0)
                else:
                    manifest, k, v = ray_tpu.get(ref, timeout=30.0)
                rid = self.engine.adopt_pages(manifest, k, v)
            except Exception:  # noqa: BLE001
                self._handoff_fallbacks += 1
                return None
            if rid is None:
                self._handoff_fallbacks += 1
                return None
            self._handoff_bytes += int(k.nbytes) + int(v.nbytes)
            self._handoff_s += time.monotonic() - t0
            self._handoffs += 1
            return rid

        # -- request surface ---------------------------------------------
        def __call__(self, request):
            self._ensure_model(request)
            prompt = request["prompt"]
            gen = _gen_from_request(request)
            text = self.engine.generate([prompt], gen)[0]
            self._note_first_token()
            return {"prompt": prompt, "generated_text": text}

        def stream_tokens(self, request):
            """Generator-based token streaming: call with
            ``.options(num_returns="streaming")`` and iterate the
            ObjectRefGenerator — each decoded token text seals as its own
            object with normal object-plane semantics."""
            if not hasattr(self.engine, "stream_ids"):
                raise TypeError(
                    "token streaming requires engine='continuous'"
                )
            self._ensure_model(request)
            gen = _gen_from_request(request)
            prompt = self.engine.tokenizer.encode(request["prompt"])
            for tok in self.engine.stream_ids(prompt, gen):
                self._note_first_token()
                yield self.engine.tokenizer.decode([int(tok)])

        def stream_to(self, writer, request):
            """Router/ingress streaming contract: decoded token text
            through a ChannelWriter-compatible handle (shm ring same-host,
            PushWriter cross-host, relay actor legacy). ``resume_from=n``
            regenerates deterministically and skips the first n tokens —
            the router's mid-stream failover path."""
            if not hasattr(self.engine, "stream_ids"):
                writer.write("streaming requires engine='continuous'")
                writer.close_channel()
                return 0
            self._ensure_model(request)
            gen = _gen_from_request(request)
            skip = max(0, int(request.get("resume_from", 0)))
            prompt = self.engine.tokenizer.encode(request["prompt"])
            # disaggregated handoff: graft the prefill worker's KV pages
            # and stream from the adopted slot — no local prefill. Any
            # handoff failure falls through to stream_ids (local
            # re-prefill), the same path a resume_from failover takes.
            rid = None
            handoff = (
                request.get("handoff")
                if isinstance(request, dict)
                else None
            )
            if handoff and not skip and hasattr(self.engine, "adopt_pages"):
                rid = self._adopt_handoff(handoff)
            tokens = (
                self.engine.stream_rid(rid)
                if rid is not None
                else self.engine.stream_ids(prompt, gen)
            )
            n = 0
            for tok in tokens:
                self._note_first_token()
                if n >= skip:
                    writer.write(self.engine.tokenizer.decode([int(tok)]))
                n += 1
                self._tokens_out += 1
            writer.close_channel()
            return n

        # -- online-RL hot-swap (ISSUE 20) -------------------------------
        def swap_weights_ref(self, request) -> dict:
            """Install params shipped through the OBJECT PLANE — the
            online-RL publish path, where the weights are genuinely new
            (trained this run) rather than a pre-built variant. The tree
            lands from the ref, is registered as a variant (so
            ``_ensure_model`` routing and replica restarts resolve the
            model id), pushed into the node hub for same-node siblings,
            then installed under the usual epoch-fenced drain."""
            from ray_tpu.serve import model_store as ms

            model = request["model"]
            version = int(request.get("version", 0))
            new_params = ray_tpu.get(request["params_ref"], timeout=60.0)
            with self._swap_lock:
                if model == self.engine.model_id:
                    return {
                        "model": model,
                        "epoch": self.engine.weights_epoch,
                        "swapped": False,
                    }
                labels = {"deployment": name, "model": str(model)}
                t0 = time.monotonic()
                self._variants[model] = new_params
                if self._hub is not None:
                    self._hub.ensure(model, version, new_params)
                epoch = self.engine.swap_params(new_params, model_id=model)
                now = time.monotonic()
                ms.WEIGHT_SWAP_MS.observe(
                    (now - t0) * 1000.0, labels=labels
                )
                ms.WEIGHT_SWAPS.inc(labels=labels)
                self._swap_done_t = now
                self._swaps += 1
                return {"model": model, "epoch": epoch, "swapped": True}

        # -- observability -----------------------------------------------
        def pid(self) -> int:
            return os.getpid()

        def serve_stats(self) -> dict:
            stats = (
                self.engine.stats()
                if hasattr(self.engine, "stats")
                else {}
            )
            return {
                "pid": os.getpid(),
                "tokens_out": self._tokens_out,
                "weight_swaps": self._swaps,
                "first_token_new_weights_count": self._ft_new_count,
                "first_token_new_weights_ms_sum": round(
                    self._ft_new_ms_sum, 3
                ),
                "handoffs": self._handoffs,
                "handoff_fallbacks": self._handoff_fallbacks,
                "handoff_bytes": self._handoff_bytes,
                "handoff_s": round(self._handoff_s, 6),
                "kv_handoff_mb_per_s": (
                    round(
                        self._handoff_bytes / self._handoff_s / (1 << 20), 2
                    )
                    if self._handoff_s > 0
                    else None
                ),
                **stats,
            }

        def _start_agent_reporter(self) -> None:
            """Inside a cluster worker: push engine/prefix stats to the
            node agent so its DebugState grows a ``serve`` block (node-
            local control-plane traffic, never the head)."""
            from ray_tpu.cluster import worker as worker_mod

            w = getattr(worker_mod, "_CURRENT_WORKER", None)
            if w is None or not hasattr(self.engine, "stats"):
                return
            # weakref: the reporter must not keep a killed replica's
            # engine alive (or the thread running) past the actor's
            # lifetime — a strong capture leaked the whole KV pool per
            # replica churn and blocked worker scrub/reuse
            import weakref

            ref = weakref.ref(self)

            def loop():
                import time as _time

                from ray_tpu.config import cfg

                while True:
                    _time.sleep(max(0.2, float(cfg.serve_report_period_s)))
                    inst = ref()
                    if inst is None:
                        return  # replica collected: thread retires
                    try:
                        w.agent.call(
                            "ServeStats",
                            {
                                "pid": os.getpid(),
                                "deployment": name,
                                "stats": inst.serve_stats(),
                            },
                            timeout=5.0,
                        )
                    except Exception:  # noqa: BLE001 - agent mid-restart
                        pass
                    del inst

            threading.Thread(
                target=loop, name="serve-stats-report", daemon=True
            ).start()

    if prefill_replicas:
        # the companion prefill fleet: runs the bucketed prefill
        # program, seals the KV pages + manifest as its task result
        # (device frames when the plane is on), never decodes. Deployed
        # EAGERLY here so the decode router's prefill orchestration
        # finds it registered the moment the decode app runs.
        @serve.deployment(
            name=prefill_dep_name,
            num_replicas=prefill_replicas,
            stats_method="serve_stats",
            models=models,
        )
        class PrefillServer:
            def __init__(self):
                self.engine = _make_engine(base_model_id)
                self._variants = dict(variants or {})
                self._variants[base_model_id] = self.engine.params
                self._swap_lock = threading.Lock()

            def prefill(self, request):
                """One prefill phase: returns ``(manifest, k, v)`` — the
                sealed KV pages for the prompt plus the page-table
                manifest (first token included; it is sampled from the
                same deterministic per-request key stream decode uses)."""
                model = (
                    request.get("model")
                    if isinstance(request, dict)
                    else None
                )
                if model and model != self.engine.model_id:
                    with self._swap_lock:
                        if model != self.engine.model_id:
                            new_params = self._variants.get(model)
                            if new_params is None:
                                raise ValueError(
                                    f"unknown model {model!r} for "
                                    f"prefill fleet {prefill_dep_name!r}"
                                )
                            self.engine.swap_params(
                                new_params, model_id=model
                            )
                gen = _gen_from_request(request)
                prompt = self.engine.tokenizer.encode(request["prompt"])
                return self.engine.prefill_extract(prompt, gen)

            def pid(self) -> int:
                return os.getpid()

            def serve_stats(self) -> dict:
                return {
                    "pid": os.getpid(),
                    "role": "prefill",
                    **self.engine.stats(),
                }

        serve.run(PrefillServer.bind())

    return LLMServer.bind()
