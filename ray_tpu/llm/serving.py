"""LLM serving: engine replicas behind ray_tpu.serve.

Analog of the reference's serve-side LLM deployments (/root/reference/
python/ray/llm/_internal/serve/): build_llm_deployment returns a Serve
application whose replicas each hold an engine; requests are
{"prompt": str, "max_new_tokens"?: int, "temperature"?: float}.

Serving-plane integration (PR 8):

- replicas of a ``continuous`` deployment share prefilled KV through
  the node's shm arena (:mod:`ray_tpu.serve.prefix_cache`) — a repeated
  prompt prefix is a pinned read-only view copy-in, not a prefill;
- streams are **resumable**: generation is per-request deterministic
  (seeded), so ``stream_to`` honors ``resume_from=n`` by regenerating
  and skipping the first ``n`` tokens — the router uses this to fail a
  stream over to another replica mid-flight with no duplicated or lost
  acked tokens. Caveat: exactness assumes the resumed replica computes
  the same logits as the original. The cache-hit suffix-prefill kernel
  and the full-prefill kernel differ in reduction shape, so their
  logits can differ in the last ulps; if the original and failover
  replicas take DIFFERENT prefill paths AND a sampled/argmaxed token
  sits within float epsilon of a tie, the resumed trajectory can
  diverge. Real models' logit gaps dwarf that epsilon (the chaos
  suite's token-exact invariant has never tripped on it), but the
  guarantee is probabilistic at the ulp level, not bitwise;
- replicas report engine + prefix-cache stats to their node agent
  (DebugState ``serve`` block) and expose ``serve_stats`` to the
  router's head reporter (QueryState("serve")).
"""
from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Optional

import ray_tpu.serve as serve
from .engine import GenerationConfig, LLMEngine


def _params_sig(model_config: Any, params: Optional[Any], name: str) -> str:
    """Cheap weight signature for the shared prefix cache: KV computed
    under different weights must never collide. Hashes the config repr
    plus a slice of the first parameter leaf (or the default-init
    marker when params is None)."""
    h = hashlib.sha256(f"{name}:{model_config}".encode())
    if params is None:
        h.update(b"default-init-seed0")
    else:
        import jax
        import numpy as np

        leaves = jax.tree_util.tree_leaves(params)
        h.update(str(len(leaves)).encode())
        if leaves:
            first = np.asarray(leaves[0]).ravel()[:256]
            h.update(first.tobytes())
            h.update(str(np.asarray(leaves[0]).shape).encode())
    return h.hexdigest()[:24]


def _gen_from_request(request) -> GenerationConfig:
    return GenerationConfig(
        max_new_tokens=int(request.get("max_new_tokens", 32)),
        temperature=float(request.get("temperature", 0.0)),
        seed=int(request.get("seed", 0)),
    )


def build_llm_deployment(
    model_config: Any,
    params: Optional[Any] = None,
    *,
    name: str = "llm",
    num_replicas: int = 1,
    max_len: int = 256,
    engine: str = "dense",  # "dense" | "continuous" (paged KV)
    max_batch: int = 8,
    page_size: int = 16,
    n_pages: int = 256,
    prefix_cache: bool = True,
    slo: Optional[Any] = None,
):
    if engine not in ("dense", "continuous"):
        raise ValueError(
            f"unknown engine {engine!r}; expected 'dense' or 'continuous'"
        )
    model_sig = _params_sig(model_config, params, name)

    @serve.deployment(
        name=name,
        num_replicas=num_replicas,
        # continuous-engine generation is per-request deterministic
        # (seeded sampling), so streams can fail over mid-flight
        resumable_streams=(engine == "continuous"),
        stats_method="serve_stats",
        slo=slo,
    )
    class LLMServer:
        def __init__(self):
            if engine == "continuous":
                from .continuous import ContinuousBatchingEngine

                cache = None
                if prefix_cache:
                    from ray_tpu.serve.prefix_cache import cache_from_cfg

                    cache = cache_from_cfg(
                        page_size=page_size, model_sig=model_sig
                    )
                self.engine = ContinuousBatchingEngine(
                    model_config,
                    params,
                    max_batch=max_batch,
                    page_size=page_size,
                    n_pages=n_pages,
                    prefix_cache=cache,
                )
            else:
                self.engine = LLMEngine(model_config, params, max_len=max_len)
            self._tokens_out = 0
            self._start_agent_reporter()

        # -- request surface ---------------------------------------------
        def __call__(self, request):
            prompt = request["prompt"]
            gen = _gen_from_request(request)
            text = self.engine.generate([prompt], gen)[0]
            return {"prompt": prompt, "generated_text": text}

        def stream_tokens(self, request):
            """Generator-based token streaming: call with
            ``.options(num_returns="streaming")`` and iterate the
            ObjectRefGenerator — each decoded token text seals as its own
            object with normal object-plane semantics."""
            if not hasattr(self.engine, "stream_ids"):
                raise TypeError(
                    "token streaming requires engine='continuous'"
                )
            gen = _gen_from_request(request)
            prompt = self.engine.tokenizer.encode(request["prompt"])
            for tok in self.engine.stream_ids(prompt, gen):
                yield self.engine.tokenizer.decode([int(tok)])

        def stream_to(self, writer, request):
            """Router/ingress streaming contract: decoded token text
            through a ChannelWriter-compatible handle (shm ring same-host,
            PushWriter cross-host, relay actor legacy). ``resume_from=n``
            regenerates deterministically and skips the first n tokens —
            the router's mid-stream failover path."""
            if not hasattr(self.engine, "stream_ids"):
                writer.write("streaming requires engine='continuous'")
                writer.close_channel()
                return 0
            gen = _gen_from_request(request)
            skip = max(0, int(request.get("resume_from", 0)))
            prompt = self.engine.tokenizer.encode(request["prompt"])
            n = 0
            for tok in self.engine.stream_ids(prompt, gen):
                if n >= skip:
                    writer.write(self.engine.tokenizer.decode([int(tok)]))
                n += 1
                self._tokens_out += 1
            writer.close_channel()
            return n

        # -- observability -----------------------------------------------
        def pid(self) -> int:
            return os.getpid()

        def serve_stats(self) -> dict:
            stats = (
                self.engine.stats()
                if hasattr(self.engine, "stats")
                else {}
            )
            return {
                "pid": os.getpid(),
                "tokens_out": self._tokens_out,
                **stats,
            }

        def _start_agent_reporter(self) -> None:
            """Inside a cluster worker: push engine/prefix stats to the
            node agent so its DebugState grows a ``serve`` block (node-
            local control-plane traffic, never the head)."""
            from ray_tpu.cluster import worker as worker_mod

            w = getattr(worker_mod, "_CURRENT_WORKER", None)
            if w is None or not hasattr(self.engine, "stats"):
                return
            # weakref: the reporter must not keep a killed replica's
            # engine alive (or the thread running) past the actor's
            # lifetime — a strong capture leaked the whole KV pool per
            # replica churn and blocked worker scrub/reuse
            import weakref

            ref = weakref.ref(self)

            def loop():
                import time as _time

                from ray_tpu.config import cfg

                while True:
                    _time.sleep(max(0.2, float(cfg.serve_report_period_s)))
                    inst = ref()
                    if inst is None:
                        return  # replica collected: thread retires
                    try:
                        w.agent.call(
                            "ServeStats",
                            {
                                "pid": os.getpid(),
                                "deployment": name,
                                "stats": inst.serve_stats(),
                            },
                            timeout=5.0,
                        )
                    except Exception:  # noqa: BLE001 - agent mid-restart
                        pass
                    del inst

            threading.Thread(
                target=loop, name="serve-stats-report", daemon=True
            ).start()

    return LLMServer.bind()
