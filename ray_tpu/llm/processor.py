"""Batch inference processor: datasets through engine actors.

Analog of the reference's vLLM batch stage (/root/reference/python/ray/llm/
_internal/batch/stages/vllm_engine_stage.py): rows with a "prompt" column
flow through a pool of engine-holding actors via Dataset.map_batches.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from .engine import GenerationConfig, LLMEngine


@dataclass
class LLMProcessor:
    model_config: Any                       # tfm.ModelConfig
    params: Optional[Any] = None
    generation: GenerationConfig = field(default_factory=GenerationConfig)
    batch_size: int = 16
    max_len: int = 256

    def process(self, dataset):
        """dataset rows: {"prompt": str, ...} -> adds "generated_text"."""
        cfg = self.model_config
        params = self.params
        gen = self.generation
        max_len = self.max_len
        engine_holder: Dict[str, LLMEngine] = {}

        def infer(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
            # engine is constructed once per worker and reused across blocks
            if "engine" not in engine_holder:
                engine_holder["engine"] = LLMEngine(
                    cfg, params, max_len=max_len
                )
            engine = engine_holder["engine"]
            prompts = [str(p) for p in batch["prompt"]]
            outputs = engine.generate(prompts, gen)
            out = dict(batch)
            out["generated_text"] = np.array(outputs, dtype=object)
            return out

        return dataset.map_batches(infer, batch_size=self.batch_size)
