"""Native LLM engine: jitted continuous prefill+decode with KV cache."""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import transformer as tfm


@dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 32
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = no top-k filter
    seed: int = 0
    eos_token: Optional[int] = None


class ByteTokenizer:
    """Self-contained byte-level tokenizer (no external vocab files needed;
    swap in a transformers tokenizer for real checkpoints)."""

    vocab_size = 256 + 2
    bos = 256
    eos = 257

    def encode(self, text: str) -> List[int]:
        return [self.bos] + list(text.encode("utf-8"))

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", "replace")


class LLMEngine:
    """Batched generation over the flagship model.

    One jitted prefill (full prompt) + one jitted decode step re-used for
    every generated token; the KV cache buffer is donated between steps so
    decoding is in-place on device (HBM-friendly).
    """

    def __init__(
        self,
        cfg: tfm.ModelConfig,
        params: Optional[Any] = None,
        *,
        max_len: int = 256,
        tokenizer: Optional[Any] = None,
    ):
        self.cfg = cfg
        self.max_len = min(max_len, cfg.max_seq_len)
        self.tokenizer = tokenizer or ByteTokenizer()
        self.params = (
            params
            if params is not None
            else tfm.init_params(cfg, jax.random.PRNGKey(0))
        )

        @jax.jit
        def _prefill(params, tokens, lengths, cache):
            b, t = tokens.shape
            positions = jnp.arange(t)[None, :].repeat(b, 0)
            seq_mask = jnp.arange(cache["k"].shape[2])[None, :] < lengths[:, None]
            logits, cache = tfm.forward_with_cache(
                params, tokens, positions, cache, seq_mask, cfg
            )
            # logits at each sequence's last real token
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
            return last, cache

        @functools.partial(
            jax.jit, donate_argnums=(3,), static_argnums=(5, 6)
        )
        def _decode(params, token, pos, cache, key, temperature, top_k):
            b = token.shape[0]
            positions = pos[:, None]
            seq_mask = (
                jnp.arange(cache["k"].shape[2])[None, :] <= pos[:, None]
            )
            logits, cache = tfm.forward_with_cache(
                params, token[:, None], positions, cache, seq_mask, cfg
            )
            logits = logits[:, 0]
            nxt = _sample(logits, key, temperature, top_k)
            return nxt, cache

        def _sample(logits, key, temperature, top_k):
            def greedy():
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def sampled():
                scaled = logits / jnp.maximum(temperature, 1e-6)
                if self_top_k := int(top_k):
                    kth = jnp.sort(scaled, axis=-1)[:, -self_top_k][:, None]
                    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
                return jax.random.categorical(key, scaled).astype(jnp.int32)

            # temperature is a python float captured at trace time
            return greedy() if temperature == 0.0 else sampled()

        self._prefill = _prefill
        self._decode = _decode

    def generate_ids(
        self,
        prompts: List[List[int]],
        gen: GenerationConfig = GenerationConfig(),
    ) -> List[List[int]]:
        b = len(prompts)
        lengths = np.array([len(p) for p in prompts], dtype=np.int32)
        t = int(lengths.max())
        tokens = np.zeros((b, t), dtype=np.int32)
        for i, p in enumerate(prompts):
            tokens[i, : len(p)] = p
        cache = tfm.init_kv_cache(self.cfg, b, self.max_len)
        last_logits, cache = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths), cache
        )
        key = jax.random.PRNGKey(gen.seed)
        nxt = (
            jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
            if gen.temperature == 0.0
            else jax.random.categorical(
                key, last_logits / max(gen.temperature, 1e-6)
            ).astype(jnp.int32)
        )
        pos = jnp.asarray(lengths)
        out = [nxt]
        steps = min(gen.max_new_tokens - 1, self.max_len - t - 1)
        for i in range(max(0, steps)):
            key = jax.random.fold_in(key, i)
            nxt, cache = self._decode(
                self.params, nxt, pos, cache, key,
                gen.temperature, gen.top_k,
            )
            pos = pos + 1
            out.append(nxt)
        gen_tokens = np.stack([np.asarray(x) for x in out], axis=1)
        results = []
        for i in range(b):
            ids = gen_tokens[i].tolist()
            if gen.eos_token is not None and gen.eos_token in ids:
                ids = ids[: ids.index(gen.eos_token)]
            results.append(ids)
        return results

    def generate(
        self, prompts: List[str], gen: GenerationConfig = GenerationConfig()
    ) -> List[str]:
        enc = [self.tokenizer.encode(p) for p in prompts]
        cfg = gen if gen.eos_token is not None else GenerationConfig(
            max_new_tokens=gen.max_new_tokens,
            temperature=gen.temperature,
            top_k=gen.top_k,
            seed=gen.seed,
            eos_token=getattr(self.tokenizer, "eos", None),
        )
        out_ids = self.generate_ids(enc, cfg)
        return [self.tokenizer.decode(ids) for ids in out_ids]
