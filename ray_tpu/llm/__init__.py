"""ray_tpu.llm — LLM batch inference and serving on the native engine.

The reference's ray.llm is config passthrough to vLLM/SGLang
(/root/reference/python/ray/llm/_internal/). Here the engine is native:
jitted KV-cache prefill + decode on the flagship model
(ray_tpu.models.transformer), with batch inference as a Data pipeline stage
(vllm_engine_proc analog) and serving as a Serve deployment.
"""
from .continuous import ContinuousBatchingEngine, PagedKVPool  # noqa: F401
from .engine import GenerationConfig, LLMEngine  # noqa: F401
from .processor import LLMProcessor  # noqa: F401
from .serving import build_llm_deployment  # noqa: F401
