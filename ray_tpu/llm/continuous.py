"""Continuous batching engine with a paged KV cache.

The serving tier the reference delegates to vLLM-class engines
(/root/reference/python/ray/llm/_internal/serve/, vllm passthrough) —
rebuilt TPU-first in the JetStream/PagedAttention mold:

- **Paged KV pool**: one device buffer of fixed-size pages
  ``[n_layers, kv_heads, n_pages, page, head_dim]`` (head-major, so the
  Pallas decode kernel slices a head's pool without any transpose)
  shared by every sequence; a per-slot block table maps logical
  positions to pages. All
  shapes static — XLA compiles exactly two programs (per prefill bucket):
  one prefill, one decode step.
- **Continuous batching**: B decode slots; requests admit into free slots
  as others finish (no batch restart), so the decode step always runs at
  the live batch size. Admission backpressures on free pages — the pool,
  not the batch, is the capacity.
- **Decode step**: one token for ALL active slots per jit call; the KV
  write is a per-slot scatter into (page, offset) and attention gathers
  each slot's pages back into a contiguous [S_max] view (the TPU-friendly
  formulation of paged attention: gathers + one big einsum, no dynamic
  shapes).

Reference files for parity intent: vllm paged attention + continuous
batching scheduler; JetStream's slot/page design is the public TPU
pattern this follows.
"""
from __future__ import annotations

import functools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import transformer as tfm

from .engine import ByteTokenizer, GenerationConfig


@dataclass
class _Slot:
    active: bool = False
    req_id: int = -1
    pos: int = 0  # next position to write
    max_pos: int = 0  # hard stop (prompt + max_new)
    pages: List[int] = field(default_factory=list)
    out: List[int] = field(default_factory=list)
    eos: Optional[int] = None


@dataclass
class _Request:
    req_id: int
    prompt: List[int]
    gen: GenerationConfig


class PagedKVPool:
    """Fixed pool of KV pages + host-side free-list allocator."""

    def __init__(self, cfg: tfm.ModelConfig, n_pages: int, page: int):
        self.page = page
        self.n_pages = n_pages
        # head-major: [L, KH, N, page, hd] — the Pallas decode kernel and
        # the gather path both read per-head slices without a transpose
        shape = (cfg.n_layers, cfg.n_kv_heads, n_pages, page, cfg.head_dim)
        self.k = jnp.zeros(shape, cfg.dtype)
        self.v = jnp.zeros(shape, cfg.dtype)
        # page 0 is the SCRATCH page: inactive decode slots are redirected
        # there so their no-op writes can never collide with a live slot's
        # page in the same scatter (duplicate-index order is unspecified)
        self._free = list(range(1, n_pages))
        self._free_set = set(self._free)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def usable_pages(self) -> int:
        return self.n_pages - 1  # minus the scratch page

    def alloc(self, n: int) -> Optional[List[int]]:
        if len(self._free) < n:
            return None
        out = self._free[:n]
        del self._free[:n]
        self._free_set.difference_update(out)
        return out

    def free(self, pages: List[int]) -> None:
        """Return pages to the free-list. Raises on a double free (a
        page id already free, the scratch page, out-of-range, or a
        duplicate within ``pages``): silently re-adding a freed page
        would let ``alloc`` hand the same page to two slots and their
        KV scatters would corrupt each other."""
        seen = set()
        for p in pages:
            if p in seen:
                raise ValueError(
                    f"double free: page {p} appears twice in free({pages})"
                )
            if not 0 < p < self.n_pages:
                raise ValueError(
                    f"free of invalid page {p} "
                    f"(scratch page 0 / out of range, n_pages={self.n_pages})"
                )
            if p in self._free_set:
                raise ValueError(
                    f"double free: page {p} is already on the free-list "
                    "(one page allocated to two slots corrupts both "
                    "slots' KV)"
                )
            seen.add(p)
        self._free.extend(pages)
        self._free_set.update(pages)


class ContinuousBatchingEngine:
    """Slot-based continuous batching over the flagship transformer."""

    def __init__(
        self,
        cfg: tfm.ModelConfig,
        params: Optional[Any] = None,
        *,
        max_batch: int = 8,
        page_size: int = 16,
        n_pages: int = 256,
        max_pages_per_seq: Optional[int] = None,
        tokenizer: Optional[Any] = None,
        use_pallas_attention: bool = False,
        pallas_interpret: bool = False,
        prefix_cache: Optional[Any] = None,
        model_id: str = "base",
    ):
        if cfg.n_experts > 0:
            raise NotImplementedError(
                "paged continuous batching currently supports dense MLP "
                "models (use LLMEngine for MoE)"
            )
        self.cfg = cfg
        self.B = max_batch
        self.page = page_size
        self.pool = PagedKVPool(cfg, n_pages, page_size)
        self.max_pages_per_seq = min(
            max_pages_per_seq
            or (min(cfg.max_seq_len, n_pages * page_size) // page_size),
            self.pool.usable_pages,
        )
        self.tokenizer = tokenizer or ByteTokenizer()
        # opt-in Pallas paged-attention decode (ops/paged_attention.py);
        # the XLA gather formulation stays the default. The pool is
        # head-major, so the kernel slices per-head pool views with zero
        # data movement (real-TPU profiling decides the default flip)
        self.use_pallas_attention = use_pallas_attention
        self.pallas_interpret = pallas_interpret
        # optional cross-replica prefix/KV cache (serve.prefix_cache):
        # page-aligned prompt prefixes restore from pinned shm views and
        # only the suffix pays prefill compute
        self.prefix_cache = prefix_cache
        self.params = (
            params
            if params is not None
            else tfm.init_params(cfg, jax.random.PRNGKey(0))
        )
        self.slots = [_Slot() for _ in range(self.B)]
        self.queue: deque = deque()
        self.results: Dict[int, List[int]] = {}
        self._next_req = 0
        # disaggregated serving (PR 18): which weights this engine runs,
        # bumped by swap_params; manifests stamp both so a decode engine
        # never grafts KV computed under different weights
        self.model_id = model_id
        self.weights_epoch = 0
        self._swapping = False
        # bounded swap drain (ISSUE 20): when the drain outlives
        # cfg.serve_swap_drain_deadline_s, stuck slots are force-evicted
        # and parked submits get a typed Overloaded instead of hanging
        self._swap_started: Optional[float] = None
        self.swap_force_evicted = 0
        # full-prefill vs page-adoption accounting: the disagg bench's
        # zero-re-prefill gate reads these off the decode replicas
        self.full_prefill_count = 0
        self.adopted_count = 0
        # device-side slot state
        self.block_tables = jnp.full(
            (self.B, self.max_pages_per_seq), 0, dtype=jnp.int32
        )
        self.positions = jnp.zeros((self.B,), jnp.int32)
        self.cur_tokens = jnp.zeros((self.B,), jnp.int32)
        self.active_mask = jnp.zeros((self.B,), bool)
        # per-slot sampling temperature (0 = greedy) and per-slot seed:
        # each slot's key derives from its request's seed + its own
        # position, so temperature>0 output is per-request deterministic
        # regardless of which other requests are co-resident in the batch
        self.temps = jnp.zeros((self.B,), jnp.float32)
        self.seeds = jnp.zeros((self.B,), jnp.uint32)
        self._build_fns()

    # ------------------------------------------------------------------
    # jitted programs
    # ------------------------------------------------------------------
    def _build_fns(self) -> None:
        cfg = self.cfg
        page = self.page
        P_max = self.max_pages_per_seq
        S_max = P_max * page

        def _attention_pages(q, k_pages, v_pages, q_pos):
            """q: [B,H,hd] one token per slot; k/v_pages head-major
            [KH,B,P,page,hd]; q_pos: [B] query position. The einsums index
            the head-major layout directly — no materialized transpose."""
            b = q.shape[0]
            kh = cfg.n_kv_heads
            groups = cfg.n_heads // kh
            ks = k_pages.reshape(kh, b, S_max, cfg.head_dim)
            vs = v_pages.reshape(kh, b, S_max, cfg.head_dim)
            qh = q.reshape(b, kh, groups, cfg.head_dim)
            scores = jnp.einsum(
                "bhgd,hbsd->bhgs",
                qh.astype(jnp.float32),
                ks.astype(jnp.float32),
            ) / jnp.sqrt(cfg.head_dim)
            valid = jnp.arange(S_max)[None, :] <= q_pos[:, None]
            scores = jnp.where(valid[:, None, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum(
                "bhgs,hbsd->bhgd", probs, vs.astype(jnp.float32)
            )
            return attn.reshape(b, cfg.n_heads * cfg.head_dim)

        @jax.jit
        def decode_step(
            params, pool_k, pool_v, tables, positions, tokens, active,
            temps, seeds,
        ):
            """One token for every slot. Inactive slots run the same
            math (one trace) but their KV writes are redirected to the
            reserved scratch page 0, so they can never collide with a
            live slot's pages in the scatter."""
            b = self.B
            h = params["embed"][tokens].astype(cfg.dtype)  # [B, D]
            angles = tfm.rope_freqs(
                cfg.head_dim, cfg.max_seq_len, cfg.rope_theta
            )
            ang = angles[positions]  # [B, hd/2]
            page_idx = positions // page
            page_ids = jnp.take_along_axis(
                tables, page_idx[:, None], axis=1
            )[:, 0]  # [B] physical page per slot
            # inactive slots write the reserved scratch page (0): their
            # stale tables may point at pages since reallocated to a LIVE
            # slot, and a duplicate-index scatter could drop its write
            page_ids = jnp.where(active, page_ids, 0)
            offsets = jnp.where(active, positions % page, 0)

            def body(carry, layer):
                h, pk, pv = carry[0], carry[1], carry[2]
                p = layer
                x = tfm.rms_norm(h, p["ln1"])
                q = (x @ p["wq"]).reshape(b, cfg.n_heads, cfg.head_dim)
                k = (x @ p["wk"]).reshape(b, cfg.n_kv_heads, cfg.head_dim)
                v = (x @ p["wv"]).reshape(b, cfg.n_kv_heads, cfg.head_dim)
                q = _rope1(q, ang)
                k = _rope1(k, ang)
                li = carry[3]
                # head-major scatter: index arrays broadcast to [B, KH]
                hidx = jnp.arange(cfg.n_kv_heads)[None, :]
                pg_b = page_ids[:, None]
                off_b = offsets[:, None]
                pk = pk.at[li, hidx, pg_b, off_b].set(
                    jnp.where(
                        active[:, None, None],
                        k.astype(pk.dtype),
                        pk[li, hidx, pg_b, off_b],
                    )
                )
                pv = pv.at[li, hidx, pg_b, off_b].set(
                    jnp.where(
                        active[:, None, None],
                        v.astype(pv.dtype),
                        pv[li, hidx, pg_b, off_b],
                    )
                )
                if self.use_pallas_attention:
                    from ray_tpu.ops.paged_attention import (
                        paged_attention_decode,
                    )

                    kh = cfg.n_kv_heads
                    groups = cfg.n_heads // kh
                    qh = q.reshape(b, kh, groups, cfg.head_dim)
                    # pool is head-major: the kernel slices per head with
                    # ZERO data movement
                    attn = paged_attention_decode(
                        qh,
                        pk[li],
                        pv[li],
                        tables,
                        positions + 1,
                        page_size=page,
                        interpret=self.pallas_interpret,
                    ).reshape(b, cfg.n_heads * cfg.head_dim)
                else:
                    k_pages = pk[li][:, tables]  # [KH, B, P, page, hd]
                    v_pages = pv[li][:, tables]
                    attn = _attention_pages(q, k_pages, v_pages, positions)
                h = h + (attn.astype(cfg.dtype) @ p["wo"])
                x2 = tfm.rms_norm(h, p["ln2"])
                y = tfm.swiglu(x2, p["w_gate"], p["w_up"], p["w_down"])
                return (h + y, pk, pv, li + 1), None

            (h, pool_k, pool_v, _), _ = jax.lax.scan(
                body,
                (h, pool_k, pool_v, jnp.int32(0)),
                params["blocks"],
            )
            h = tfm.rms_norm(h, params["ln_f"])
            logits = (h @ params["head"]).astype(jnp.float32)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # per-slot key = fold(request seed, absolute position of the
            # token being produced); prefill samples its first token with
            # fold(seed, prompt_len), decode continues at prompt_len+1…
            # — a slot's stream never depends on co-resident requests
            sampled = jax.vmap(
                lambda sd, pos, lg, tt: jax.random.categorical(
                    jax.random.fold_in(jax.random.PRNGKey(sd), pos + 1),
                    lg / jnp.maximum(tt, 1e-6),
                )
            )(seeds, positions, logits, temps).astype(jnp.int32)
            nxt = jnp.where(temps > 0.0, sampled, greedy)
            return nxt, pool_k, pool_v

        def _rope1(x, ang):
            """x: [B, H, hd]; ang: [B, hd/2]."""
            dtype = x.dtype
            x = x.astype(jnp.float32)
            x1, x2 = jnp.split(x, 2, axis=-1)
            cos = jnp.cos(ang)[:, None, :]
            sin = jnp.sin(ang)[:, None, :]
            out = jnp.concatenate(
                [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1
            )
            return out.astype(dtype)

        @functools.partial(jax.jit, static_argnums=(4,))
        def prefill(params, pool_k, pool_v, tokens, t_pad, page_ids):
            """Prefill ONE sequence of (padded) length t_pad; write its KV
            into the given pages; return last-token logits. tokens:
            int32[t_pad]; page_ids: int32[t_pad // page]."""
            pos = jnp.arange(t_pad)
            h = params["embed"][tokens][None].astype(cfg.dtype)  # [1,T,D]
            angles = tfm.rope_freqs(
                cfg.head_dim, cfg.max_seq_len, cfg.rope_theta
            )
            ang = angles[pos][None]

            def body(carry, layer):
                h, pk, pv, li = carry
                p = layer
                x = tfm.rms_norm(h, p["ln1"])
                q = (x @ p["wq"]).reshape(1, t_pad, cfg.n_heads, cfg.head_dim)
                k = (x @ p["wk"]).reshape(
                    1, t_pad, cfg.n_kv_heads, cfg.head_dim
                )
                v = (x @ p["wv"]).reshape(
                    1, t_pad, cfg.n_kv_heads, cfg.head_dim
                )
                q = tfm._apply_rope_positions(q, ang)
                k = tfm._apply_rope_positions(k, ang)
                # causal self-attention over the prompt
                groups = cfg.n_heads // cfg.n_kv_heads
                qh = q.reshape(1, t_pad, cfg.n_kv_heads, groups, cfg.head_dim)
                scores = jnp.einsum(
                    "bthgd,bshd->bhgts",
                    qh.astype(jnp.float32),
                    k[0][None].astype(jnp.float32),
                ) / jnp.sqrt(cfg.head_dim)
                causal = (
                    jnp.arange(t_pad)[None, :] <= jnp.arange(t_pad)[:, None]
                )
                scores = jnp.where(
                    causal[None, None, None], scores, -1e30
                )
                probs = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum(
                    "bhgts,bshd->bthgd", probs, v[0][None].astype(jnp.float32)
                ).reshape(1, t_pad, -1)
                h = h + (attn.astype(cfg.dtype) @ p["wo"])
                x2 = tfm.rms_norm(h, p["ln2"])
                y = tfm.swiglu(x2, p["w_gate"], p["w_up"], p["w_down"])
                # write pages head-major: [T,KH,hd] -> [KH,T,hd] ->
                # [KH, n_pages, page, hd] (prompt-sized transpose, prefill
                # only); scatter indexes broadcast to [KH, n_pages]
                kp = jnp.transpose(k[0], (1, 0, 2)).reshape(
                    cfg.n_kv_heads, -1, page, cfg.head_dim
                )
                vp = jnp.transpose(v[0], (1, 0, 2)).reshape(
                    cfg.n_kv_heads, -1, page, cfg.head_dim
                )
                hidx = jnp.arange(cfg.n_kv_heads)[:, None]
                pk = pk.at[li, hidx, page_ids[None, :]].set(
                    kp.astype(pk.dtype)
                )
                pv = pv.at[li, hidx, page_ids[None, :]].set(
                    vp.astype(pv.dtype)
                )
                return (h + y, pk, pv, li + 1), None

            (h, pool_k, pool_v, _), _ = jax.lax.scan(
                body,
                (h, pool_k, pool_v, jnp.int32(0)),
                params["blocks"],
            )
            h = tfm.rms_norm(h, params["ln_f"])
            logits = (h[0] @ params["head"]).astype(jnp.float32)
            return logits, pool_k, pool_v

        @functools.partial(jax.jit, static_argnums=(4,))
        def prefill_suffix(
            params,
            pool_k,
            pool_v,
            tokens,
            t_pad,
            hist_len,
            table,
            suffix_page_ids,
        ):
            """Prefill the SUFFIX of a sequence whose first ``hist_len``
            tokens' KV was restored from the shared prefix cache: write
            the suffix KV into its pages, then attend over history +
            suffix by gathering the slot's whole page table (fixed
            shapes — the decode formulation applied to a prompt block;
            ``hist_len`` is traced, so one program serves every split
            within a suffix-length bucket). tokens: int32[t_pad] padded
            suffix; table: int32[P_max]; suffix_page_ids:
            int32[t_pad // page]. Returns logits over suffix positions."""
            pos = hist_len + jnp.arange(t_pad)  # absolute positions
            h = params["embed"][tokens][None].astype(cfg.dtype)
            angles = tfm.rope_freqs(
                cfg.head_dim, cfg.max_seq_len, cfg.rope_theta
            )
            ang = angles[pos][None]

            def body(carry, layer):
                h, pk, pv, li = carry
                p = layer
                x = tfm.rms_norm(h, p["ln1"])
                q = (x @ p["wq"]).reshape(
                    1, t_pad, cfg.n_heads, cfg.head_dim
                )
                k = (x @ p["wk"]).reshape(
                    1, t_pad, cfg.n_kv_heads, cfg.head_dim
                )
                v = (x @ p["wv"]).reshape(
                    1, t_pad, cfg.n_kv_heads, cfg.head_dim
                )
                q = tfm._apply_rope_positions(q, ang)
                k = tfm._apply_rope_positions(k, ang)
                # scatter the suffix KV into its pages (prefill layout)
                kp = jnp.transpose(k[0], (1, 0, 2)).reshape(
                    cfg.n_kv_heads, -1, page, cfg.head_dim
                )
                vp = jnp.transpose(v[0], (1, 0, 2)).reshape(
                    cfg.n_kv_heads, -1, page, cfg.head_dim
                )
                hidx = jnp.arange(cfg.n_kv_heads)[:, None]
                pk = pk.at[li, hidx, suffix_page_ids[None, :]].set(
                    kp.astype(pk.dtype)
                )
                pv = pv.at[li, hidx, suffix_page_ids[None, :]].set(
                    vp.astype(pv.dtype)
                )
                # history + suffix keys via the slot's full table; key
                # positions past hist_len + q_pos (incl. the scratch
                # page behind unfilled table slots) are masked
                ks = pk[li][:, table].reshape(
                    cfg.n_kv_heads, S_max, cfg.head_dim
                )
                vs = pv[li][:, table].reshape(
                    cfg.n_kv_heads, S_max, cfg.head_dim
                )
                groups = cfg.n_heads // cfg.n_kv_heads
                qh = q[0].reshape(
                    t_pad, cfg.n_kv_heads, groups, cfg.head_dim
                )
                scores = jnp.einsum(
                    "tkgd,ksd->tkgs",
                    qh.astype(jnp.float32),
                    ks.astype(jnp.float32),
                ) / jnp.sqrt(cfg.head_dim)
                causal = jnp.arange(S_max)[None, :] <= pos[:, None]
                scores = jnp.where(
                    causal[:, None, None, :], scores, -1e30
                )
                probs = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum(
                    "tkgs,ksd->tkgd", probs, vs.astype(jnp.float32)
                ).reshape(t_pad, -1)
                h = h + (attn[None].astype(cfg.dtype) @ p["wo"])
                x2 = tfm.rms_norm(h, p["ln2"])
                y = tfm.swiglu(x2, p["w_gate"], p["w_up"], p["w_down"])
                return (h + y, pk, pv, li + 1), None

            (h, pool_k, pool_v, _), _ = jax.lax.scan(
                body,
                (h, pool_k, pool_v, jnp.int32(0)),
                params["blocks"],
            )
            h = tfm.rms_norm(h, params["ln_f"])
            logits = (h[0] @ params["head"]).astype(jnp.float32)
            return logits, pool_k, pool_v

        self._decode_step = decode_step
        self._prefill = prefill
        self._prefill_suffix = prefill_suffix

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------
    def submit(self, prompt: List[int], gen: GenerationConfig) -> int:
        if gen.top_k:
            raise NotImplementedError(
                "per-slot top_k is not supported by the continuous engine "
                "(temperature sampling and greedy are); use LLMEngine"
            )
        if self._swapping and self._swap_started is not None:
            from ray_tpu.config import cfg

            deadline = float(cfg.serve_swap_drain_deadline_s)
            if deadline > 0 and (
                time.monotonic() - self._swap_started > deadline
            ):
                # the drain has outlived its budget: stop parking — the
                # caller gets a typed, retryable rejection instead of an
                # unbounded hang behind one wedged slot
                from ray_tpu.serve.admission import Overloaded

                raise Overloaded(
                    reason="weights_swap",
                    retry_after_s=min(deadline, 5.0),
                )
        prompt_pages = -(-max(len(prompt), 1) // self.page)
        if prompt_pages > self.max_pages_per_seq:
            raise ValueError(
                f"prompt of {len(prompt)} tokens needs {prompt_pages} pages "
                f"but max_pages_per_seq={self.max_pages_per_seq} "
                f"(page_size={self.page})"
            )
        rid = self._next_req
        self._next_req += 1
        self.queue.append(_Request(rid, list(prompt), gen))
        return rid

    def _pages_needed(self, req: _Request) -> int:
        total = len(req.prompt) + req.gen.max_new_tokens
        return -(-total // self.page)

    def _admit(self) -> None:
        """Fill free slots from the queue while pages are available."""
        if self._swapping:
            # weights hot-swap drain: active slots finish on the OLD
            # weights-epoch, the queue stays parked until the new
            # weights are installed — no request ever mixes epochs
            return
        for si, slot in enumerate(self.slots):
            if slot.active or not self.queue:
                continue
            req = self.queue[0]
            need = min(self._pages_needed(req), self.max_pages_per_seq)
            pages = self.pool.alloc(need)
            if pages is None:
                break  # backpressure: the POOL is the capacity
            self.queue.popleft()
            prompt = req.prompt
            t = len(prompt)
            # shared prefix cache: restore the longest cached page-aligned
            # prefix as pinned shm views, capped so the LAST real token
            # always runs a live forward pass (its logits seed sampling)
            hit = None
            if self.prefix_cache is not None and t > 1:
                hit = self.prefix_cache.lookup(
                    prompt, max_tokens=((t - 1) // self.page) * self.page
                )
            table = np.zeros(self.max_pages_per_seq, np.int32)
            table[: len(pages)] = pages
            if hit is not None:
                last_logits = self._admit_with_prefix(req, pages, table, hit)
            else:
                t_pad = max(self.page, -(-t // self.page) * self.page)
                prompt_pages = t_pad // self.page
                tokens = np.zeros(t_pad, np.int32)
                tokens[:t] = prompt
                logits, self.pool.k, self.pool.v = self._prefill(
                    self.params,
                    self.pool.k,
                    self.pool.v,
                    jnp.asarray(tokens),
                    t_pad,
                    jnp.asarray(pages[:prompt_pages], dtype=jnp.int32),
                )
                last_logits = logits[t - 1]
                self.full_prefill_count += 1
            if self.prefix_cache is not None:
                # publish this prompt's full pages for other replicas
                # (reads the pool AFTER prefill wrote it — the np gather
                # below is also what synchronizes the device work)
                self._prefix_insert(
                    prompt, pages, hit.tokens if hit is not None else 0
                )
            first = self._sample_first(req.gen, last_logits, t)
            if hit is not None:
                # np conversions above synced every consumer of the
                # pinned views; dropping them releases the arena pin
                hit.release()
            slot.active = True
            slot.req_id = req.req_id
            slot.pos = t
            # the prefill already produced token #1, so decode runs
            # max_new-1 steps; the last token is never written back
            slot.max_pos = min(
                t + req.gen.max_new_tokens - 1, len(pages) * self.page
            )
            slot.pages = pages
            slot.eos = req.gen.eos_token  # parity with LLMEngine.generate_ids
            slot.out = [first]
            # device state (table was built before prefill — the suffix
            # path passes the whole row to its gather)
            self.block_tables = self.block_tables.at[si].set(
                jnp.asarray(table)
            )
            self.positions = self.positions.at[si].set(t)
            self.cur_tokens = self.cur_tokens.at[si].set(first)
            self.active_mask = self.active_mask.at[si].set(True)
            self.temps = self.temps.at[si].set(float(req.gen.temperature))
            self.seeds = self.seeds.at[si].set(
                np.uint32(req.gen.seed & 0xFFFFFFFF)
            )
            self._maybe_finish(si)

    def _admit_with_prefix(self, req, pages, table, hit):
        """Cache-hit admission: copy the pinned KV views into this
        engine's pool pages and prefill only the suffix. Returns the
        last real token's logits."""
        t = len(req.prompt)
        hist_pages = hit.tokens // self.page
        dev_pages = jnp.asarray(pages[:hist_pages], dtype=jnp.int32)
        # device-frame hits are ALREADY jax Arrays (landed straight from
        # the arena page — the device plane removed the intermediate
        # host copy); host-view hits keep the old path, where
        # jnp.asarray may alias the pinned view on the CPU backend —
        # safe because every consumer below is synced before release()
        k_src, v_src = hit.k, hit.v
        if isinstance(k_src, np.ndarray):
            k_src = jnp.asarray(np.asarray(k_src))
        if isinstance(v_src, np.ndarray):
            v_src = jnp.asarray(np.asarray(v_src))
        self.pool.k = self.pool.k.at[:, :, dev_pages].set(k_src)
        self.pool.v = self.pool.v.at[:, :, dev_pages].set(v_src)
        suffix = req.prompt[hit.tokens :]
        ts = len(suffix)
        t_pad = max(self.page, -(-ts // self.page) * self.page)
        suffix_pages = t_pad // self.page
        tokens = np.zeros(t_pad, np.int32)
        tokens[:ts] = suffix
        logits, self.pool.k, self.pool.v = self._prefill_suffix(
            self.params,
            self.pool.k,
            self.pool.v,
            jnp.asarray(tokens),
            t_pad,
            jnp.int32(hit.tokens),
            jnp.asarray(table),
            jnp.asarray(
                pages[hist_pages : hist_pages + suffix_pages],
                dtype=jnp.int32,
            ),
        )
        return logits[ts - 1]

    def _prefix_insert(self, prompt, pages, covered: int) -> None:
        """Publish the prompt's FULL pages (already in the pool) to the
        shared cache — skipped when the hit already covered them."""
        ins = (len(prompt) // self.page) * self.page
        if ins <= covered or ins == 0:
            return
        n_pages = ins // self.page
        if n_pages > len(pages):
            return
        if getattr(self.prefix_cache, "contains_prefix", None) and (
            self.prefix_cache.contains_prefix(prompt[:ins])
        ):
            # already published (hot prompt): skip the device→host KV
            # gather entirely — it's a blocking sync on the admit path
            return
        dev = jnp.asarray(pages[:n_pages], dtype=jnp.int32)
        from ray_tpu.cluster import device_plane as _dp

        if _dp.device_plane_enabled():
            # the gathered KV block stays a device buffer: the cache's
            # seal exports it as a device frame (zero-copy where the
            # backend aliases host memory, chunked D2H pump elsewhere) —
            # the eager np.asarray device→host sync is gone from the
            # admit path, and lookups on the other side land the pages
            # back on device with one device_put
            k = self.pool.k[:, :, dev]
            v = self.pool.v[:, :, dev]
        else:
            k = np.asarray(self.pool.k[:, :, dev])
            v = np.asarray(self.pool.v[:, :, dev])
        self.prefix_cache.insert(prompt[:ins], k, v)

    def _sample_first(self, gen: GenerationConfig, last_logits, t: int) -> int:
        if gen.temperature > 0.0:
            # same uint32 normalization as the decode path — one key
            # stream per request across prefill and decode
            kk = jax.random.fold_in(
                jax.random.PRNGKey(np.uint32(gen.seed & 0xFFFFFFFF)),
                t,
            )
            return int(
                jax.random.categorical(
                    kk,
                    jnp.asarray(last_logits)
                    / max(gen.temperature, 1e-6),
                )
            )
        return int(np.asarray(last_logits).argmax())

    # ------------------------------------------------------------------
    # disaggregated serving: prefill/decode split (PR 18)
    # ------------------------------------------------------------------
    def prefill_extract(self, prompt: List[int], gen: GenerationConfig):
        """Prefill-worker half of the KV handoff: run the bucketed
        prefill program for ``prompt``, sample the first token
        (host-side, per-request deterministic — the same
        ``fold_in(seed, t)`` stream a monolithic admit uses), gather the
        prompt pages out of the pool, and free them. Returns
        ``(manifest, k, v)`` where ``k``/``v`` are
        ``[L, KH, prompt_pages, page, hd]`` blocks — device buffers when
        the device plane is on (the wire layer seals them as device
        frames, so the ship to a decode replica rides the striped
        peer-socket plane and lands with one ``device_put``), host
        copies otherwise (the host-bounce fallback)."""
        t = len(prompt)
        if t < 1:
            raise ValueError("prefill_extract needs a non-empty prompt")
        t_pad = max(self.page, -(-t // self.page) * self.page)
        prompt_pages = t_pad // self.page
        if prompt_pages > self.max_pages_per_seq:
            raise ValueError(
                f"prompt of {t} tokens needs {prompt_pages} pages but "
                f"max_pages_per_seq={self.max_pages_per_seq}"
            )
        pages = self.pool.alloc(prompt_pages)
        if pages is None:
            raise MemoryError(
                "prefill pool exhausted "
                f"(free={self.pool.free_pages}, need={prompt_pages})"
            )
        try:
            tokens = np.zeros(t_pad, np.int32)
            tokens[:t] = prompt
            logits, self.pool.k, self.pool.v = self._prefill(
                self.params,
                self.pool.k,
                self.pool.v,
                jnp.asarray(tokens),
                t_pad,
                jnp.asarray(pages, dtype=jnp.int32),
            )
            self.full_prefill_count += 1
            first = self._sample_first(gen, logits[t - 1], t)
            dev = jnp.asarray(pages, dtype=jnp.int32)
            from ray_tpu.cluster import device_plane as _dp

            if _dp.device_plane_enabled():
                # functional jax arrays: these gathers are new buffers,
                # so freeing the pool pages below cannot alias them
                k = self.pool.k[:, :, dev]
                v = self.pool.v[:, :, dev]
            else:
                k = np.asarray(self.pool.k[:, :, dev])
                v = np.asarray(self.pool.v[:, :, dev])
        finally:
            self.pool.free(pages)
        manifest = {
            "prompt": list(prompt),
            "t": t,
            "first": int(first),
            "pages": prompt_pages,
            "page": self.page,
            "gen": {
                "max_new_tokens": int(gen.max_new_tokens),
                "temperature": float(gen.temperature),
                "seed": int(gen.seed),
                "eos_token": gen.eos_token,
            },
            "model": self.model_id,
            "weights_epoch": self.weights_epoch,
        }
        return manifest, k, v

    def adopt_pages(self, manifest: dict, k, v) -> Optional[int]:
        """Decode-engine half of the KV handoff: graft prefilled KV
        pages straight into this engine's pool and admit the request
        mid-batch — no prefill program runs here (the zero-re-prefill
        property the disagg bench gates on). Returns the new req_id, or
        None when the handoff cannot be adopted (mismatched page
        geometry or model, no free slot, pool backpressure) — the
        caller falls back to ``submit()``, i.e. a local re-prefill,
        which is token-exact because generation is seed-deterministic."""
        if manifest.get("page") != self.page:
            return None
        if manifest.get("model", self.model_id) != self.model_id:
            # KV computed under different weights: grafting it would mix
            # weights-epochs inside one batch — refuse, re-prefill
            return None
        gen = GenerationConfig(**manifest["gen"])
        prompt = list(manifest["prompt"])
        t = int(manifest["t"])
        ship_pages = int(manifest["pages"])
        si = next(
            (i for i, s in enumerate(self.slots) if not s.active), None
        )
        if si is None:
            return None
        need = min(
            -(-(t + gen.max_new_tokens) // self.page),
            self.max_pages_per_seq,
        )
        need = max(need, ship_pages)
        if need > self.max_pages_per_seq:
            return None
        pages = self.pool.alloc(need)
        if pages is None:
            return None  # pool backpressure: the POOL is the capacity
        rid = self._next_req
        self._next_req += 1
        dev = jnp.asarray(pages[:ship_pages], dtype=jnp.int32)
        if isinstance(k, np.ndarray):
            k = jnp.asarray(k)
        if isinstance(v, np.ndarray):
            v = jnp.asarray(v)
        self.pool.k = self.pool.k.at[:, :, dev].set(
            k.astype(self.pool.k.dtype)
        )
        self.pool.v = self.pool.v.at[:, :, dev].set(
            v.astype(self.pool.v.dtype)
        )
        table = np.zeros(self.max_pages_per_seq, np.int32)
        table[: len(pages)] = pages
        first = int(manifest["first"])
        slot = self.slots[si]
        slot.active = True
        slot.req_id = rid
        slot.pos = t
        slot.max_pos = min(
            t + gen.max_new_tokens - 1, len(pages) * self.page
        )
        slot.pages = pages
        slot.eos = gen.eos_token
        slot.out = [first]
        self.block_tables = self.block_tables.at[si].set(
            jnp.asarray(table)
        )
        self.positions = self.positions.at[si].set(t)
        self.cur_tokens = self.cur_tokens.at[si].set(first)
        self.active_mask = self.active_mask.at[si].set(True)
        self.temps = self.temps.at[si].set(float(gen.temperature))
        self.seeds = self.seeds.at[si].set(
            np.uint32(gen.seed & 0xFFFFFFFF)
        )
        self.adopted_count += 1
        self._maybe_finish(si)
        return rid

    # ------------------------------------------------------------------
    # weights hot-swap (PR 18 model multiplexing)
    # ------------------------------------------------------------------
    def swap_params(self, params: Any, model_id: Optional[str] = None) -> int:
        """Install new weights with epoch-fenced drain semantics (the
        gang-epoch pattern applied to a replica's weights): admission
        parks, every ACTIVE slot finishes its generation on the old
        weights-epoch, then the swap lands and the epoch bumps — no
        in-flight stream ever crosses weights. Queued requests stay
        queued and admit on the NEW weights. Returns the new epoch.

        The drain is bounded by ``cfg.serve_swap_drain_deadline_s``
        (0 = legacy unbounded): past the deadline, still-active slots are
        force-evicted — their output is recorded truncated at the tokens
        generated so far, so a wedged generation can park the whole
        replica for at most one deadline, never forever."""
        from ray_tpu.config import cfg

        deadline = float(cfg.serve_swap_drain_deadline_s)
        self._swapping = True
        self._swap_started = time.monotonic()
        try:
            while any(s.active for s in self.slots):
                if deadline > 0 and (
                    time.monotonic() - self._swap_started > deadline
                ):
                    self._force_evict_active()
                    break
                self.step()
            self.params = params
            if model_id is not None:
                self.model_id = model_id
            self.weights_epoch += 1
            if self.prefix_cache is not None:
                # KV cached under the OLD weights must never be restored
                # for the new ones — re-namespace the shared cache so
                # every stale prefix misses (engines swapping to the
                # same model id keep sharing the new namespace)
                self.prefix_cache.retag(
                    self.model_id
                    if model_id is not None
                    else f"swap{self.weights_epoch}"
                )
        finally:
            self._swapping = False
            self._swap_started = None
        return self.weights_epoch

    def _force_evict_active(self) -> None:
        """Evict every still-active slot at the swap-drain deadline: the
        partial output lands in results (eos-truncated like a normal
        finish) so readers unblock, pages free, and the slot resets."""
        for si, slot in enumerate(self.slots):
            if not slot.active:
                continue
            out = slot.out
            if slot.eos is not None and slot.eos in out:
                out = out[: out.index(slot.eos)]
            self.results[slot.req_id] = out
            self.pool.free(slot.pages)
            self.slots[si] = _Slot()
            self.active_mask = self.active_mask.at[si].set(False)
            self.swap_force_evicted += 1

    def _maybe_finish(self, si: int) -> None:
        slot = self.slots[si]
        done = (
            slot.pos >= slot.max_pos
            or (slot.eos is not None and slot.out and slot.out[-1] == slot.eos)
        )
        if done and slot.active:
            out = slot.out
            if slot.eos is not None and slot.eos in out:
                out = out[: out.index(slot.eos)]
            self.results[slot.req_id] = out
            self.pool.free(slot.pages)
            self.slots[si] = _Slot()
            self.active_mask = self.active_mask.at[si].set(False)

    def step(self) -> List[int]:
        """Admit + one decode step for all active slots. Returns req_ids
        finished in this step."""
        self._admit()
        before = set(self.results)
        if any(s.active for s in self.slots):
            nxt, self.pool.k, self.pool.v = self._decode_step(
                self.params,
                self.pool.k,
                self.pool.v,
                self.block_tables,
                self.positions,
                self.cur_tokens,
                self.active_mask,
                self.temps,
                self.seeds,
            )
            nxt_h = np.asarray(nxt)
            self.positions = self.positions + jnp.where(self.active_mask, 1, 0)
            self.cur_tokens = nxt
            for si, slot in enumerate(self.slots):
                if not slot.active:
                    continue
                slot.pos += 1
                slot.out.append(int(nxt_h[si]))
                self._maybe_finish(si)
        return [r for r in self.results if r not in before]

    def pending(self) -> int:
        return len(self.queue) + sum(s.active for s in self.slots)

    # ------------------------------------------------------------------
    def generate_ids(
        self,
        prompts: List[List[int]],
        gen: GenerationConfig = GenerationConfig(),
    ) -> List[List[int]]:
        ids = [self.submit(p, gen) for p in prompts]
        while any(i not in self.results for i in ids):
            self.step()
        return [self.results.pop(i) for i in ids]

    def stream_ids(
        self,
        prompt: List[int],
        gen: GenerationConfig = GenerationConfig(),
    ):
        """Incremental generation: yields token ids as decode steps produce
        them (the engine keeps serving any other in-flight requests in the
        same steps). The serving tier pipes this through a
        ray_tpu.experimental Channel for cross-process token streaming."""
        rid = self.submit(prompt, gen)
        yield from self.stream_rid(rid)

    def stream_rid(self, rid: int):
        """Stream tokens for an already-registered request id — either
        one queued via ``submit()`` or one grafted mid-batch via
        ``adopt_pages()`` (the disaggregated handoff path, where no
        local prefill ever runs)."""
        yielded = 0
        try:
            while rid not in self.results:
                self.step()
                slot = next(
                    (s for s in self.slots if s.req_id == rid and s.active),
                    None,
                )
                if slot is not None:
                    out = slot.out
                    if slot.eos is not None and slot.eos in out:
                        out = out[: out.index(slot.eos)]
                    while yielded < len(out):
                        yield out[yielded]
                        yielded += 1
            final = self.results.pop(rid)
            while yielded < len(final):
                yield final[yielded]
                yielded += 1
        finally:
            # consumer abandoned mid-stream: reclaim the slot's pages and
            # stop burning decode steps on a dead client
            self._cancel(rid)

    def _cancel(self, rid: int) -> None:
        """Drop a request wherever it is: queued, active, or finished."""
        self.results.pop(rid, None)
        for i, req in enumerate(self.queue):
            if req.req_id == rid:
                del self.queue[i]
                return
        for si, slot in enumerate(self.slots):
            if slot.active and slot.req_id == rid:
                self.pool.free(slot.pages)
                self.slots[si] = _Slot()
                self.active_mask = self.active_mask.at[si].set(False)
                return

    def generate(
        self, prompts: List[str], gen: GenerationConfig = GenerationConfig()
    ) -> List[str]:
        enc = [self.tokenizer.encode(p) for p in prompts]
        if gen.eos_token is None:
            gen = GenerationConfig(
                max_new_tokens=gen.max_new_tokens,
                temperature=gen.temperature,
                top_k=gen.top_k,
                seed=gen.seed,
                eos_token=getattr(self.tokenizer, "eos", None),
            )
        out = self.generate_ids(enc, gen)
        return [self.tokenizer.decode(ids) for ids in out]

    def stats(self) -> dict:
        out = {
            "free_pages": self.pool.free_pages,
            "total_pages": self.pool.n_pages,
            "active_slots": sum(s.active for s in self.slots),
            "queued": len(self.queue),
            "model_id": self.model_id,
            "weights_epoch": self.weights_epoch,
            "full_prefill_count": self.full_prefill_count,
            "adopted_count": self.adopted_count,
            "swap_force_evicted": self.swap_force_evicted,
        }
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out
