"""Sacrificial owner process for owner-crash chaos.

A real driver in its own process: it connects to the head (establishing
an owner session lease), creates non-detached actors, optionally parks
one never-finishing task (an UNPRODUCED object whose fate the reap
decides), then keeps light task traffic flowing until it is SIGKILLed by
the chaos orchestrator / tests. It writes a JSON info file (client id,
actor ids, pending ref) once everything is ALIVE so the killer knows
exactly what must be reaped.

The point of a separate process is that the kill is REAL: no
DisconnectClient, no atexit — the head must notice purely through missed
owner heartbeats and run the full reap (kill actors, revoke leases,
cancel tasks, fail unproduced objects with OwnerDiedError).
"""
from __future__ import annotations

import argparse
import json
import os
import time


def _hang(seconds: float) -> bytes:
    time.sleep(seconds)
    return b"hang-done"


def _small(i: int) -> bytes:
    return bytes([i % 251]) * 4096


class _OwnedActor:
    """Plain non-detached actor; dies with its owner."""

    def ping(self) -> str:
        return "pong"


def main() -> None:
    parser = argparse.ArgumentParser(description="sacrificial chaos owner")
    parser.add_argument("--head", required=True)
    parser.add_argument("--info-file", required=True)
    parser.add_argument("--actors", type=int, default=1)
    parser.add_argument(
        "--hang-task",
        action="store_true",
        help="park one max_retries=0 task so an unproduced object exists",
    )
    parser.add_argument("--hang-seconds", type=float, default=600.0)
    args = parser.parse_args()

    import ray_tpu

    rt = ray_tpu.init(address=args.head)
    Actor = ray_tpu.remote(_OwnedActor)
    handles = [Actor.remote() for _ in range(max(0, args.actors))]
    for h in handles:
        # report only once every actor is ALIVE: the killer's invariant
        # ("reaped within one liveness window") starts from real state
        ray_tpu.get(h.ping.remote(), timeout=120)
    hang_ref = None
    if args.hang_task:
        hang_ref = (
            ray_tpu.remote(_hang)
            .options(max_retries=0)
            .remote(args.hang_seconds)
        )
    info = {
        "pid": os.getpid(),
        "client_id": rt.client_id,
        "actor_ids": [h._actor_id for h in handles],
        "hang_ref": hang_ref.hex if hang_ref is not None else None,
    }
    tmp = args.info_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, args.info_file)

    task = ray_tpu.remote(_small)
    i = 0
    while True:  # until SIGKILL
        refs = [task.remote(i + k) for k in range(2)]
        i += 2
        try:
            ray_tpu.get(refs, timeout=30)
        except Exception:  # noqa: BLE001 - traffic is best-effort
            pass
        for h in handles:
            h.ping.remote()
        time.sleep(0.25)


if __name__ == "__main__":
    main()
