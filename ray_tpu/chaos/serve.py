"""Serving-plane chaos workload: verified token streams under faults.

Drives a *resumable* streaming deployment (the LLM continuous engine —
per-request deterministic generation) through the serving router while
the orchestrator injects ``replica_kill`` faults, and verifies the
serving plane's core promise end to end: every completed stream's token
sequence equals the expected sequence EXACTLY — a mid-stream replica
SIGKILL that fails over may neither duplicate nor drop a single acked
token.

The workload doubles as the orchestrator's ``serve_adapter``: it knows
how to pick a live replica worker pid to kill, how many replicas are
supposed to exist (the replica set's desired count), and whether
streams kept completing after the fault.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import ray_tpu


class ServeStreamWorkload:
    """``concurrency`` threads open stream after stream against
    ``router`` and verify each completed stream against
    ``expected_tokens`` (the deterministic reference sequence)."""

    def __init__(
        self,
        router,
        payload: dict,
        expected_tokens: List[str],
        concurrency: int = 2,
    ):
        self.router = router
        self.payload = dict(payload)
        self.expected = list(expected_tokens)
        self.concurrency = concurrency
        self.completed = 0
        self.stream_errors = 0
        self.verify_failures: List[str] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- stream loop ----------------------------------------------------
    def _loop(self) -> None:
        from ray_tpu.serve.router import ChannelClosed

        while not self._stop.is_set():
            got: List[str] = []
            stream = None
            try:
                stream = self.router.stream(self.payload)
                while True:
                    try:
                        got.append(stream.read(timeout=30.0))
                    except ChannelClosed:
                        break
            except Exception:  # noqa: BLE001
                # failover exhaustion surfaces here; only token
                # CORRUPTION is an invariant failure — a hard error on
                # an unlucky double-kill is counted but tolerated
                with self._lock:
                    self.stream_errors += 1
                time.sleep(0.2)
                continue
            finally:
                if stream is not None:
                    stream.close()
            if got != self.expected:
                div = next(
                    (
                        i
                        for i, (a, b) in enumerate(zip(got, self.expected))
                        if a != b
                    ),
                    min(len(got), len(self.expected)),
                )
                with self._lock:
                    self.verify_failures.append(
                        f"stream returned {len(got)} tokens, expected "
                        f"{len(self.expected)}; first divergence at "
                        f"index {div} (duplicated/dropped acked tokens)"
                    )
            else:
                with self._lock:
                    self.completed += 1

    def start(self) -> None:
        for i in range(self.concurrency):
            t = threading.Thread(
                target=self._loop, name=f"serve-chaos-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- orchestrator adapter surface -----------------------------------
    def pick_replica_pid(self, rng) -> Optional[int]:
        """A live replica worker's pid (victim selection); None when no
        replica answers."""
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        if not replicas:
            return None
        for r in rng.sample(replicas, len(replicas)):
            try:
                return int(
                    ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                )
            except Exception:  # noqa: BLE001 - already dead: next
                continue
        return None

    def live_replicas(self) -> int:
        """Replicas that actually answer a call right now."""
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        alive = 0
        for r in replicas:
            try:
                ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                alive += 1
            except Exception:  # noqa: BLE001
                pass
        return alive

    def target_replicas(self) -> int:
        return self.router._rs.target
