"""Serving-plane chaos workload: verified token streams under faults.

Drives a *resumable* streaming deployment (the LLM continuous engine —
per-request deterministic generation) through the serving router while
the orchestrator injects ``replica_kill`` / ``router_kill`` faults, and
verifies the serving plane's core promise end to end: every completed
stream's token sequence equals the expected sequence EXACTLY — a
mid-stream replica SIGKILL (or ingress-router kill, when ``router`` is
a :class:`~ray_tpu.serve.fleet.RouterFleet`) that fails over may
neither duplicate nor drop a single acked token.

The workload doubles as the orchestrator's ``serve_adapter``: it knows
how to pick a live replica worker pid (or a live router) to kill, how
many replicas are supposed to exist, and whether the streams that were
in flight at a fault completed token-exact afterwards.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import ray_tpu


class ServeStreamWorkload:
    """``concurrency`` threads open stream after stream against
    ``router`` and verify each completed stream against
    ``expected_tokens`` (the deterministic reference sequence). With a
    fleet and multiple ``tenants``, the threads' streams spread across
    the routers (consistent-hash assignment), so a router kill lands
    mid-stream."""

    def __init__(
        self,
        router,
        payload: dict,
        expected_tokens: List[str],
        concurrency: int = 2,
        tenants: Optional[List[str]] = None,
        prefill_rs=None,
    ):
        self.router = router
        self.payload = dict(payload)
        self.expected = list(expected_tokens)
        self.concurrency = concurrency
        self.tenants = list(tenants or ["default"])
        # disaggregated deployments: the prefill tier's replica set, so
        # prefill_kill can pick victims and verify backfill. None for
        # monolithic deployments (prefill faults then report skipped).
        self.prefill_rs = prefill_rs
        self.completed = 0
        self.stream_errors = 0
        self.verify_failures: List[str] = []
        self.routers_killed = 0
        # router-kill accounting: stream_id -> outcome ("ok" |
        # "verify_fail" | "error") for every stream that was IN FLIGHT
        # at the moment of a router kill — the cross-router resume
        # invariant reads this
        self._watched: Dict[str, str] = {}
        self._inflight: Dict[int, object] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- stream loop ----------------------------------------------------
    def _loop(self, idx: int) -> None:
        from ray_tpu.serve.router import ChannelClosed

        tenant = self.tenants[idx % len(self.tenants)]
        while not self._stop.is_set():
            got: List[str] = []
            stream = None
            sid = None
            try:
                stream = self.router.stream(self.payload, tenant)
                sid = getattr(stream, "stream_id", None)
                with self._lock:
                    self._inflight[idx] = stream
                while True:
                    try:
                        got.append(stream.read(timeout=30.0))
                    except ChannelClosed:
                        break
            except Exception:  # noqa: BLE001
                # failover exhaustion surfaces here; only token
                # CORRUPTION is an invariant failure — a hard error on
                # an unlucky double-kill is counted but tolerated
                with self._lock:
                    self.stream_errors += 1
                    self._inflight.pop(idx, None)
                    if sid in self._watched:
                        self._watched[sid] = "error"
                time.sleep(0.2)
                continue
            finally:
                if stream is not None:
                    stream.close()
            ok = got == self.expected
            if not ok:
                div = next(
                    (
                        i
                        for i, (a, b) in enumerate(zip(got, self.expected))
                        if a != b
                    ),
                    min(len(got), len(self.expected)),
                )
                with self._lock:
                    self.verify_failures.append(
                        f"stream returned {len(got)} tokens, expected "
                        f"{len(self.expected)}; first divergence at "
                        f"index {div} (duplicated/dropped acked tokens)"
                    )
            else:
                with self._lock:
                    self.completed += 1
            with self._lock:
                self._inflight.pop(idx, None)
                if sid in self._watched:
                    self._watched[sid] = "ok" if ok else "verify_fail"

    def start(self) -> None:
        for i in range(self.concurrency):
            t = threading.Thread(
                target=self._loop,
                args=(i,),
                name=f"serve-chaos-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)

    # -- orchestrator adapter surface -----------------------------------
    def pick_replica_pid(self, rng) -> Optional[int]:
        """A live replica worker's pid (victim selection); None when no
        replica answers."""
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        if not replicas:
            return None
        for r in rng.sample(replicas, len(replicas)):
            try:
                return int(
                    ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                )
            except Exception:  # noqa: BLE001 - already dead: next
                continue
        return None

    def live_replicas(self) -> int:
        """Replicas that actually answer a call right now."""
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        alive = 0
        for r in replicas:
            try:
                ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                alive += 1
            except Exception:  # noqa: BLE001
                pass
        return alive

    def target_replicas(self) -> int:
        return self.router._rs.target

    # -- prefill-tier adapter surface ------------------------------------
    def pick_prefill_pid(self, rng) -> Optional[int]:
        """A live PREFILL worker's pid (prefill_kill victim selection);
        None when the deployment is monolithic or no prefill replica
        answers."""
        rs = self.prefill_rs
        if rs is None:
            return None
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        if not replicas:
            return None
        for r in rng.sample(replicas, len(replicas)):
            try:
                return int(
                    ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                )
            except Exception:  # noqa: BLE001 - already dead: next
                continue
        return None

    def live_prefill(self) -> int:
        """Prefill replicas that actually answer a call right now."""
        rs = self.prefill_rs
        if rs is None:
            return 0
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        alive = 0
        for r in replicas:
            try:
                ray_tpu.get(r.actor.pid.remote(), timeout=10.0)
                alive += 1
            except Exception:  # noqa: BLE001
                pass
        return alive

    def target_prefill(self) -> int:
        return self.prefill_rs.target if self.prefill_rs else 0

    # -- router-fleet adapter surface ------------------------------------
    def kill_router(self, rng) -> Optional[str]:
        """Abruptly kill one live ingress router (fleet deployments
        only), preferring one that currently owns in-flight streams so
        the kill actually lands mid-stream. Snapshots those streams
        into the cross-router resume watchlist. Returns the victim's
        router id, or None when no kill is possible (single router /
        plain ServeRouter)."""
        fleet = self.router
        if not hasattr(fleet, "chaos_kill_router"):
            return None
        with self._lock:
            inflight = [
                s
                for s in self._inflight.values()
                if getattr(s, "stream_id", None) is not None
            ]
        owned: Dict[str, List[object]] = {}
        for s in inflight:
            owned.setdefault(getattr(s, "_rid", ""), []).append(s)
        victim = None
        candidates = [rid for rid, _ in fleet.live_routers() if rid in owned]
        if candidates:
            victim = rng.choice(sorted(candidates))
        # register the watchlist BEFORE the kill: a stream completing in
        # the gap then records "ok" instead of dangling as pending
        pre = [s.stream_id for s in owned.get(victim, ())] if victim else []
        with self._lock:
            for sid in pre:
                self._watched.setdefault(sid, "pending")
        rid = fleet.chaos_kill_router(rid=victim, rng=rng)
        if rid is None:
            with self._lock:
                for sid in pre:
                    if self._watched.get(sid) == "pending":
                        del self._watched[sid]
            return None
        with self._lock:
            self.routers_killed += 1
        return rid

    def watched_outcomes(self) -> Dict[str, str]:
        """Outcome per stream that was in flight on a killed router."""
        with self._lock:
            return dict(self._watched)

    def routers_live(self) -> int:
        fleet = self.router
        if not hasattr(fleet, "live_routers"):
            return 1
        return len(fleet.live_routers())
