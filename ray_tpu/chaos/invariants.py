"""Post-fault invariant checks.

After every injected fault the orchestrator waits for the cluster to
converge and then asserts, in order:

1. **Membership** — every agent process that should be alive is ALIVE at
   the head (killed nodes excluded; partitioned nodes re-register after
   heal).
2. **No acked-object loss** — a sample of results the driver already
   observed still resolves to byte-identical values (lineage rebuilds
   dropped copies; a restarted head re-seeds its directory from agent
   store inventories).
3. **Actor recovery** — every restartable workload actor is ALIVE at the
   head AND answers a method call within the restart budget.
4. **Lease drain** — in-flight submissions either complete or fail with
   a definite exhausted-retry/dead-actor error; nothing hangs.
5. **Durable-state match** — after a head restart, the recovered KV
   entries and named-actor bindings equal the pre-fault snapshot.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .workload import ChaosWorkload


@dataclass
class Snapshot:
    """Durable head state captured before a fault."""

    kv: Dict[str, bytes] = field(default_factory=dict)
    named_actors: Dict[str, str] = field(default_factory=dict)


@dataclass
class CheckResult:
    ok: bool
    failures: List[str] = field(default_factory=list)


class InvariantChecker:
    def __init__(
        self,
        cluster,
        workload: ChaosWorkload,
        actor_restart_budget_s: float = 60.0,
        object_timeout_s: float = 60.0,
    ):
        self.cluster = cluster
        self.workload = workload
        self.actor_restart_budget_s = actor_restart_budget_s
        self.object_timeout_s = object_timeout_s

    # -- pre-fault ------------------------------------------------------
    def snapshot(self) -> Snapshot:
        head = self.cluster.head
        with head._lock:
            return Snapshot(
                kv=dict(head._kv),
                named_actors=dict(head._named_actors),
            )

    # -- convergence ----------------------------------------------------
    def expected_alive(self) -> int:
        """Agent processes still running = nodes that must be ALIVE."""
        return sum(
            1 for p in self.cluster._agents.values() if p.poll() is None
        )

    def wait_membership(self, deadline: float) -> Optional[str]:
        want = self.expected_alive()
        while time.monotonic() < deadline:
            alive = sum(
                1 for n in self.cluster.head.nodes.values() if n.alive
            )
            if alive >= want:
                return None
            time.sleep(0.1)
        alive = sum(1 for n in self.cluster.head.nodes.values() if n.alive)
        return f"membership: {alive}/{want} nodes alive at the head"

    def wait_actors(self, deadline: float) -> List[str]:
        import ray_tpu

        failures: List[str] = []
        for handle, aid in zip(
            self.workload.actors, self.workload.actor_ids
        ):
            recovered = False
            while time.monotonic() < deadline:
                info = self.cluster.head._actors.get(aid)
                state = info.state if info is not None else "UNKNOWN"
                if state == "ALIVE":
                    try:
                        budget = max(1.0, deadline - time.monotonic())
                        if (
                            ray_tpu.get(
                                handle.ping.remote(), timeout=budget
                            )
                            == "pong"
                        ):
                            recovered = True
                            break
                    except Exception:  # noqa: BLE001 - retry until budget
                        pass
                elif state == "DEAD":
                    failures.append(
                        f"actor {aid[:8]} is DEAD (restart budget was "
                        "not exhausted by the plan)"
                    )
                    recovered = True  # definite, stop polling
                    break
                time.sleep(0.2)
            if not recovered:
                failures.append(
                    f"actor {aid[:8]} not responsive within the "
                    f"{self.actor_restart_budget_s}s restart budget"
                )
        return failures

    def check_leases_drained(self, timeout: float) -> List[str]:
        """Every pending submission resolves or fails definitively."""
        self.workload.ack(timeout=timeout)
        failures = [
            f"lease for object {ref.hex[:8]} hung (neither completed "
            "nor failed definitively)"
            for ref, _ in self.workload.pending
        ]
        # definite failures are legal ONLY as exhausted-retry /
        # dead-actor / cancelled errors
        for h, reason in self.workload.failed_pending:
            low = reason.lower()
            if not any(
                key in low
                for key in (
                    "retries exhausted",
                    "retry",
                    "died",
                    "dead",
                    "cancelled",
                    "unreachable",
                    "lost",
                )
            ):
                failures.append(
                    f"lease for object {h[:8]} failed with an "
                    f"unexpected error: {reason}"
                )
        self.workload.failed_pending.clear()
        return failures

    def wait_owner_reaped(self, cid: str, timeout: float) -> List[str]:
        """After an owner SIGKILL: within the budget the head must hold
        ZERO live non-detached actors, ZERO task-lease rows, and no
        session for that client — the full fate-sharing reap."""
        head = self.cluster.head
        deadline = time.monotonic() + timeout
        actors: List[str] = []
        leases: List[str] = []
        session = True
        while time.monotonic() < deadline:
            with head._lock:
                actors = [
                    a.actor_id
                    for a in head._actors.values()
                    if a.owner_client == cid
                    and a.lifetime != "detached"
                    and a.state != "DEAD"
                ]
                leases = [
                    lid
                    for lid, e in head._task_leases.items()
                    if e.get("client_id") == cid
                ]
                session = cid in head._owner_sessions
            if not actors and not leases and not session:
                return []
            time.sleep(0.2)
        out = []
        if actors:
            out.append(
                f"owner {cid[:8]} leaked {len(actors)} live actors "
                f"after death"
            )
        if leases:
            out.append(
                f"owner {cid[:8]} leaked {len(leases)} worker leases "
                f"after death"
            )
        if session:
            out.append(f"owner {cid[:8]} session never declared dead")
        return out

    def wait_standby_promoted(
        self, pre_epoch: int, timeout: float
    ) -> List[str]:
        """After a head_kill_promote: within the budget the cluster must
        have EXACTLY ONE leader — a promoted head whose epoch strictly
        exceeds the killed leader's — and every other head incarnation
        this cluster ever ran must be down or self-fenced (its writes
        provably rejected)."""
        from ray_tpu.cluster.rpc import RpcClient

        deadline = time.monotonic() + timeout
        head = self.cluster.head
        while time.monotonic() < deadline:
            head = self.cluster.head
            if (
                getattr(head, "role", "leader") == "leader"
                and not getattr(head, "_fenced", False)
                and not getattr(head, "_shutdown", False)
                and head.cluster_epoch > pre_epoch
            ):
                # every prior incarnation provably inert: its listener
                # is down, or what still answers identifies as fenced
                # (self-fenced deposed leader, writes rejected)
                live_old_leaders = []
                for h in getattr(self.cluster, "_dead_heads", []):
                    probe = RpcClient(h.address)
                    try:
                        role = probe.call("HeadRole", {}, timeout=2.0)
                    except Exception:  # noqa: BLE001 - listener down: inert
                        continue
                    finally:
                        probe.close()
                    if (
                        isinstance(role, dict)
                        and role.get("role") == "leader"
                        and role.get("epoch") == h.cluster_epoch
                    ):
                        live_old_leaders.append(h.address)
                if not live_old_leaders:
                    return []
                return [
                    "split-brain: prior head(s) still answering as "
                    f"leader: {live_old_leaders}"
                ]
            time.sleep(0.05)
        return [
            "standby never promoted: head epoch "
            f"{getattr(head, 'cluster_epoch', 0)} vs pre-kill "
            f"{pre_epoch}, role={getattr(head, 'role', '?')}, "
            f"fenced={getattr(head, '_fenced', '?')} after {timeout:.0f}s"
        ]

    def wait_inflight_survive(self, adapter, timeout: float) -> List[str]:
        """After a failover: every lease wave submitted BEFORE the kill
        completes (or fails definitively) through the new leader with
        zero acked-object loss; active serve streams (when a serve
        adapter drives them) keep completing token-exact."""
        failures = self.check_leases_drained(timeout=timeout)
        failures += self.workload.verify_acked(timeout=timeout)
        if adapter is not None:
            failures += self.wait_streams_resume(adapter, timeout=timeout)
        return failures

    def wait_streams_resume(self, adapter, timeout: float) -> List[str]:
        """After a replica_kill: in-flight streams must fail over (or
        restart) and KEEP COMPLETING with byte-exact token sequences —
        any recorded verification failure means an acked token was
        duplicated or dropped, an immediate invariant breach."""
        if adapter is None:
            return ["replica_kill injected with no serve adapter"]
        deadline = time.monotonic() + timeout
        base = adapter.completed
        while time.monotonic() < deadline:
            if adapter.verify_failures:
                return list(adapter.verify_failures)
            if adapter.completed > base:
                return []
            time.sleep(0.2)
        if adapter.verify_failures:
            return list(adapter.verify_failures)
        return [
            f"no stream completed within {timeout:.0f}s after the "
            "replica kill (streams wedged instead of failing over)"
        ]

    def wait_streams_resume_cross_router(
        self, adapter, timeout: float
    ) -> List[str]:
        """After a router_kill: EVERY stream that was in flight on the
        killed router must complete token-exact on a sibling — the
        replicated delivered-count checkpoint plus the consumer-side
        skip window may neither duplicate nor drop one acked delta.
        A watched stream erroring out entirely is also a breach (the
        failover path wedged), unlike replica_kill where hard errors
        on unlucky double-kills are tolerated."""
        if adapter is None:
            return ["router_kill injected with no serve adapter"]
        watched = getattr(adapter, "watched_outcomes", None)
        if watched is None:
            return ["router_kill injected but adapter tracks no streams"]
        deadline = time.monotonic() + timeout
        outcomes: dict = {}
        while time.monotonic() < deadline:
            if adapter.verify_failures:
                return list(adapter.verify_failures)
            outcomes = watched()
            if outcomes and all(
                v != "pending" for v in outcomes.values()
            ):
                break
            if not outcomes:
                break  # kill landed with nothing in flight: nothing owed
            time.sleep(0.2)
        failures = []
        bad = sorted(
            sid for sid, v in outcomes.items() if v != "ok"
        )
        if bad:
            failures.append(
                f"{len(bad)}/{len(outcomes)} in-flight stream(s) did "
                "not resume token-exact on a sibling router after the "
                f"kill: {[f'{s[:8]}={outcomes[s]}' for s in bad]}"
            )
        # and the fleet keeps serving: fresh streams still complete
        failures += self.wait_streams_resume(
            adapter, timeout=max(1.0, deadline - time.monotonic())
        )
        return failures

    def wait_replica_backfilled(self, adapter, timeout: float) -> List[str]:
        """After a replica_kill the replica set must restore its desired
        count with replicas that actually answer calls."""
        if adapter is None:
            return []
        deadline = time.monotonic() + timeout
        live = 0
        while time.monotonic() < deadline:
            live = adapter.live_replicas()
            if live >= adapter.target_replicas():
                return []
            time.sleep(0.3)
        return [
            f"replica set not backfilled: {live}/"
            f"{adapter.target_replicas()} live replicas after "
            f"{timeout:.0f}s"
        ]

    def wait_prefill_backfilled(self, adapter, timeout: float) -> List[str]:
        """After a prefill_kill the prefill tier must restore its desired
        replica count with workers that actually answer calls. Adapters
        without a prefill surface owe nothing (monolithic deployment)."""
        if adapter is None or getattr(adapter, "prefill_rs", None) is None:
            return []
        deadline = time.monotonic() + timeout
        live = 0
        while time.monotonic() < deadline:
            live = adapter.live_prefill()
            if live >= adapter.target_prefill():
                return []
            time.sleep(0.3)
        return [
            f"prefill tier not backfilled: {live}/"
            f"{adapter.target_prefill()} live prefill workers after "
            f"{timeout:.0f}s"
        ]

    def arena_zombies(self) -> int:
        """Sum of deleted-with-outstanding-pins entries across every live
        node's arena (agent DebugState ``object_plane.arena_zombies``)."""
        from ray_tpu.cluster.rpc import RpcClient

        total = 0
        head = self.cluster.head
        with head._lock:
            nodes = [
                (nid, n.address) for nid, n in head.nodes.items() if n.alive
            ]
        for nid, addr in nodes:
            client = RpcClient(addr)
            try:
                state = client.call("DebugState", timeout=10.0)
                total += int(
                    (state.get("object_plane") or {}).get("arena_zombies", 0)
                )
            except Exception:  # noqa: BLE001 - node mid-death
                pass
            finally:
                client.close()
        return total

    def wait_arena_zombies_zero(self, timeout: float = 15.0) -> int:
        """Poll until the cluster-wide zombie count reaches zero (frees
        may still be in flight right after the last fault); returns the
        final count (0 = invariant holds)."""
        deadline = time.monotonic() + timeout
        count = self.arena_zombies()
        while count > 0 and time.monotonic() < deadline:
            time.sleep(0.5)
            count = self.arena_zombies()
        return count

    def wait_gang_reshaped(
        self, prekill_epochs: Dict[str, int], timeout: float
    ) -> List[str]:
        """Elastic-training invariant after a rank_node_kill: every gang
        that had a member on the corpse either advances its epoch past
        the pre-kill value AND re-registers a membership whose nodes are
        all alive (the reshaped generation), or finishes and
        unregisters. Reads ``cluster.head`` each poll — the head object
        can be replaced by a failover mid-soak."""
        deadline = time.monotonic() + timeout
        failures: List[str] = []
        while time.monotonic() < deadline:
            head = self.cluster.head
            with head._lock:
                gangs = {
                    gid: {
                        "epoch": g["epoch"],
                        "members": dict(g["members"]),
                    }
                    for gid, g in head._gangs.items()
                }
                alive = {
                    nid for nid, n in head.nodes.items() if n.alive
                }
            failures = []
            for gid, pre_epoch in prekill_epochs.items():
                g = gangs.get(gid)
                if g is None:
                    continue  # finished + unregistered: converged
                if g["epoch"] <= pre_epoch:
                    failures.append(
                        f"gang {gid}: epoch {g['epoch']} never advanced "
                        f"past pre-kill {pre_epoch}"
                    )
                elif not set(g["members"].values()) <= alive:
                    failures.append(
                        f"gang {gid}: reshaped membership still names "
                        f"dead node(s) "
                        f"{sorted(set(g['members'].values()) - alive)}"
                    )
            if not failures:
                return []
            time.sleep(0.3)
        return failures

    def wait_weights_epoch_converged(
        self, rl_adapter, timeout: float
    ) -> List[str]:
        """Online-RL invariant (ISSUE 20): the fleet converges on the
        published weights epoch — live rollout replicas span at most ONE
        epoch between them and none sits below ``published - 1``. While
        the trainer keeps publishing, one swap is always legitimately in
        flight toward some replica (so demanding bit-equal epochs at a
        sampled instant would flake against a moving frontier); a swap
        that is actually LOST still trips this, because the dead
        replica falls ever further behind as publishes keep landing on
        its peers. Also asserts publish atomicity: the committed epoch
        the control plane reports never reads torn (a sealed-but-
        uncommitted phase must coexist with the OLD committed value)."""
        deadline = time.monotonic() + timeout
        failures: List[str] = []
        while time.monotonic() < deadline:
            failures = []
            try:
                published = int(rl_adapter.published_epoch())
                epochs = list(rl_adapter.replica_epochs())
            except Exception as e:  # noqa: BLE001 - control plane moving
                failures = [f"weights epoch state unreadable: {e!r}"]
                time.sleep(0.3)
                continue
            if not epochs:
                failures.append("no live rollout replica reported an epoch")
            elif max(epochs) - min(epochs) > 1:
                failures.append(
                    f"replica weights epochs diverged: {sorted(epochs)} "
                    f"(published={published})"
                )
            elif min(epochs) < published - 1:
                failures.append(
                    f"replica stuck {published - min(epochs)} epochs "
                    f"behind published {published}"
                )
            if not failures:
                return []
            time.sleep(0.3)
        return failures

    def wait_trajectory_accounting(
        self, rl_adapter, timeout: float
    ) -> List[str]:
        """Online-RL conservation law: every emitted trajectory is
        trained, dropped stale, or still in flight — zero unaccounted.
        A trajectory silently lost (or silently trained twice) breaks
        ``emitted == trained + dropped_stale + in_flight``."""
        deadline = time.monotonic() + timeout
        failures: List[str] = []
        while time.monotonic() < deadline:
            try:
                acct = dict(rl_adapter.trajectory_accounting())
            except Exception as e:  # noqa: BLE001
                failures = [f"trajectory accounting unreadable: {e!r}"]
                time.sleep(0.3)
                continue
            if acct.get("unaccounted", None) == 0:
                return []
            failures = [f"trajectory accounting does not balance: {acct}"]
            time.sleep(0.3)
        return failures

    def check_durable_state(self, pre: Snapshot) -> List[str]:
        head = self.cluster.head
        failures: List[str] = []
        with head._lock:
            kv = dict(head._kv)
            named = dict(head._named_actors)
        for key, value in pre.kv.items():
            if kv.get(key) != value:
                failures.append(
                    f"durable kv {key!r} diverged after recovery"
                )
        for name, aid in pre.named_actors.items():
            if named.get(name) != aid:
                failures.append(
                    f"named actor {name!r} lost its binding after recovery"
                )
        return failures

    def check_convergence(self, pre: Snapshot) -> CheckResult:
        deadline = time.monotonic() + self.actor_restart_budget_s
        failures: List[str] = []
        miss = self.wait_membership(deadline)
        if miss:
            failures.append(miss)
        failures.extend(self.wait_actors(deadline))
        failures.extend(
            self.check_leases_drained(timeout=self.object_timeout_s)
        )
        failures.extend(
            self.workload.verify_acked(timeout=self.object_timeout_s)
        )
        failures.extend(self.check_durable_state(pre))
        return CheckResult(ok=not failures, failures=failures)
