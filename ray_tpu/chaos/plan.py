"""Deterministic fault plans.

The reference validates fault tolerance with a chaos release suite
(node-killer actors injected while invariant checks run,
release/nightly_tests chaos_test/* and python/ray/_private/test_utils.py
RayletKiller). Ours is deterministic end-to-end: a plan is a pure
function of ``(seed, num_faults, mix)`` — replaying the same seed
reproduces the exact same fault schedule, so any soak failure is
replayable with ``RAY_TPU_CHAOS_SEED``.

Targets are drawn as raw integers at plan time and resolved modulo the
live node set at injection time: the schedule stays fixed even though
cluster membership changes as faults kill and replace nodes.
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

# fault kind -> default mix weight
DEFAULT_MIX: Tuple[Tuple[str, float], ...] = (
    ("partition", 3.0),
    ("straggler", 3.0),
    ("object_drop", 3.0),
    ("kill_node", 2.0),
    ("owner_kill", 1.5),
    ("zygote_kill", 1.5),
    ("head_restart", 1.0),
)

# serving-plane mix: adds replica_kill (SIGKILL a serve replica's worker
# mid-stream) and prefill_kill (SIGKILL a PREFILL-tier worker of a
# disaggregated deployment mid-handoff; decode replicas must fall back
# to local re-prefill and every stream must stay token-exact). Not in
# DEFAULT_MIX — the generic soak runs no serve workload, and keeping the
# default mix stable preserves seed-for-seed schedule reproducibility
# across versions. Plans that drive a serve workload pass this mix (or
# an explicit allow list over it); monolithic serve workloads without a
# prefill tier report prefill_kill faults as skipped.
SERVE_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("replica_kill", 2.0),
    ("prefill_kill", 1.5),
)

# cross-node transport mix: adds peer_conn_drop (sever one node's data
# sockets mid-transfer; in-flight striped pulls must resume, not
# restart). Not in DEFAULT_MIX for the same seed-stability reason as
# replica_kill — plans that drive cross-node transfers pass this mix.
NET_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("peer_conn_drop", 2.0),
)

# replicated-control-plane mix: adds head_kill_promote (SIGKILL the
# leader, a pre-armed warm standby must detect + promote, and in-flight
# work must complete with zero acked loss). Not in DEFAULT_MIX — the
# generic soak arms no standby, and the default schedule must stay
# seed-stable; plans built by the failover soak pass this mix.
FAILOVER_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("head_kill_promote", 1.0),
)

# elastic-training mix: adds rank_node_kill (SIGKILL a node HOSTING
# elastic gang ranks, chosen from the head's gang table). The gang must
# fence its epoch, reshape to the surviving topology, and resume from
# object-plane seals — no disk restore. Not in DEFAULT_MIX for the same
# seed-stability reason; plans that drive an elastic training workload
# pass this mix.
TRAIN_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("rank_node_kill", 2.0),
)

# elasticity-plane mix: adds node_drain (cooperative drain-ahead of a
# live node — the head zeroes its advertised capacity, preemptively
# migrates leased work off it BEFORE the deadline, then retires it; all
# retryable work must land elsewhere with zero attempts burned). Not in
# DEFAULT_MIX for the same seed-stability reason — plans that exercise
# the unified elasticity controller (PR 19) pass this mix.
ELASTIC_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("node_drain", 2.0),
)

# router-fleet mix: adds router_kill on top of the serve mix (abruptly
# kill one ingress router of a fleet mid-stream; the sibling inheriting
# the hash range must resume every in-flight stream token-exact from
# the replicated delivered-count checkpoints). Not in DEFAULT_MIX or
# SERVE_MIX for the same seed-stability reason — plans that drive a
# multi-router fleet pass this mix.
ROUTER_MIX: Tuple[Tuple[str, float], ...] = SERVE_MIX + (
    ("router_kill", 2.0),
)

# online-RL mix: the triple-plane soak (ISSUE 20). rollout_kill SIGKILLs
# a rollout replica mid-trajectory (token-exact resume via resume_from),
# trainer_rank_kill SIGKILLs a node hosting elastic-gang ranks of the RL
# trainer mid-step (gang reshape, loss-curve continuity vs reference),
# and head_kill_mid_publish kills the leader INSIDE the seal->commit
# window of a two-phase weights publish (standby promotes; the epoch is
# either fully old or fully new, never torn). Not in DEFAULT_MIX for the
# same seed-stability reason — plans that drive the online-RL workload
# pass this mix.
RL_MIX: Tuple[Tuple[str, float], ...] = DEFAULT_MIX + (
    ("rollout_kill", 2.0),
    ("trainer_rank_kill", 2.0),
    ("head_kill_mid_publish", 1.0),
)

KINDS = tuple(k for k, _ in ROUTER_MIX) + (
    "peer_conn_drop",
    "head_kill_promote",
    "rank_node_kill",
    "node_drain",
    "rollout_kill",
    "trainer_rank_kill",
    "head_kill_mid_publish",
)


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault. ``target`` picks a node (modulo the live set
    at injection time); ``magnitude`` in [0,1) scales kind-specific
    parameters (partition hold, straggler delay peak); ``delay_s`` is the
    pause after the previous fault converges."""

    index: int
    kind: str
    delay_s: float
    target: int
    magnitude: float


@dataclass
class ChaosPlan:
    seed: int
    faults: List[FaultSpec] = field(default_factory=list)

    def counts(self) -> dict:
        out: dict = {}
        for f in self.faults:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out


def make_plan(
    seed: int,
    num_faults: int,
    mix: Sequence[Tuple[str, float]] = DEFAULT_MIX,
    allow: Optional[Sequence[str]] = None,
    min_delay_s: float = 0.05,
    max_delay_s: float = 0.5,
) -> ChaosPlan:
    """Deterministic plan: same arguments -> identical schedule."""
    pairs = [
        (k, w) for k, w in mix if allow is None or k in allow
    ]
    if not pairs:
        raise ValueError("fault mix is empty after applying allow-list")
    kinds = [k for k, _ in pairs]
    weights = [w for _, w in pairs]
    rng = random.Random(seed)
    faults = [
        FaultSpec(
            index=i,
            kind=rng.choices(kinds, weights=weights)[0],
            delay_s=rng.uniform(min_delay_s, max_delay_s),
            target=rng.randrange(1 << 30),
            magnitude=rng.random(),
        )
        for i in range(num_faults)
    ]
    return ChaosPlan(seed=seed, faults=faults)
