"""Online-RL chaos workload (ISSUE 20): the triple-plane soak adapter.

Extends the serve-plane stream workload with the RL surfaces the
orchestrator's ``rollout_kill`` / ``trainer_rank_kill`` /
``head_kill_mid_publish`` faults need:

- **Epoch-aware verification.** Rollout streams are deterministic given
  the weights epoch, so each completed stream is verified against the
  reference sequence for the model it was SUBMITTED under (the driver
  registers one per published epoch). A mid-kill resume may neither
  duplicate nor drop an acked token — and a stream can never silently
  mix two epochs, because a mixed stream matches neither reference.
- **Trajectory emission.** Every verified stream becomes a trajectory
  (stamped with its epoch) emitted into the :class:`TrajectoryFeed`,
  so the conservation-law invariant covers the real rollout path.
- **The publish-hold kill window.** ``arm_publish_hold`` latches the
  publisher's ``between_phases`` hook: the next publish parks between
  seal and commit, the orchestrator SIGKILLs the leader inside that
  window, and ``release_publish_hold`` lets the publisher's retry land
  against the promoted standby.
"""
from __future__ import annotations

import re
import threading
import zlib
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.chaos.serve import ServeStreamWorkload

_EPOCH_RE = re.compile(r"epoch-(\d+)$")


def model_epoch(model_id: Optional[str]) -> int:
    """Published weights epoch encoded in a model id (``epoch-N``);
    0 for the base model."""
    m = _EPOCH_RE.search(model_id or "")
    return int(m.group(1)) if m else 0


class RLRolloutWorkload(ServeStreamWorkload):
    """Rollout streams through the serve router, verified per weights
    epoch, feeding trajectories to the trainer. Doubles as the
    orchestrator's ``rl_adapter``."""

    def __init__(
        self,
        router,
        payload: dict,
        expected_by_model: Dict[str, List[str]],
        *,
        publisher,
        feed,
        concurrency: int = 2,
        tenants: Optional[List[str]] = None,
        token_space: int = 65536,
    ):
        base_model = payload.get("model", "base")
        super().__init__(
            router,
            # pin the model id explicitly: replicas honor the pin via
            # _ensure_model, so a stream submitted (or RESUMED after a
            # kill) against a replica whose weights already moved swaps
            # back instead of silently serving the wrong epoch — without
            # the pin there is a broadcast→register window where fresh
            # streams verify against the old reference but run on new
            # weights
            {**payload, "model": base_model},
            expected_tokens=list(expected_by_model.get(base_model, [])),
            concurrency=concurrency,
            tenants=tenants,
        )
        self.publisher = publisher
        self.feed = feed
        # id range for hashed trajectory tokens — MUST be <= the trainer
        # model's vocab_size when the trajectories are actually trained
        # on (an out-of-vocab label NaNs the CE loss)
        self.token_space = int(token_space)
        self.trainer = None  # driver sets once the ElasticTrainer is up
        self._expected_by_model = {
            m: list(t) for m, t in expected_by_model.items()
        }
        self._traj_seq = 0
        # publish-hold latch (head_kill_mid_publish window)
        self._hold_requested = threading.Event()
        self._in_window = threading.Event()
        self._release = threading.Event()
        publisher.between_phases = self._between_phases

    # -- epoch-aware driver surface --------------------------------------
    def register_model(
        self, model_id: str, expected_tokens: List[str]
    ) -> None:
        """Register the reference sequence for a freshly published
        epoch's model, and route NEW streams to it."""
        with self._lock:
            self._expected_by_model[model_id] = list(expected_tokens)
            self.payload = {**self.payload, "model": model_id}

    def broadcast_weights(self, params, model_id: str, version: int):
        """Push published params to every live replica through the
        object plane (``swap_weights_ref``) — including replicas
        backfilled after a rollout kill, which start on base weights.
        Best-effort per replica; the convergence invariant is the
        judge."""
        ref = ray_tpu.put(params)
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        swapped = 0
        for r in replicas:
            try:
                ray_tpu.get(
                    r.actor.swap_weights_ref.remote(
                        {
                            "model": model_id,
                            "version": int(version),
                            "params_ref": ref,
                        }
                    ),
                    timeout=60.0,
                )
                swapped += 1
            except Exception:  # noqa: BLE001 - dead replica: judged later
                pass
        return swapped

    # -- stream loop (epoch-aware verification + trajectory emission) ----
    def _loop(self, idx: int) -> None:  # noqa: C901
        from ray_tpu.serve.router import ChannelClosed

        tenant = self.tenants[idx % len(self.tenants)]
        while not self._stop.is_set():
            got: List[str] = []
            stream = None
            sid = None
            with self._lock:
                payload = dict(self.payload)
                self._traj_seq += 1
                seq = self._traj_seq
            model = payload.get("model", "base")
            try:
                stream = self.router.stream(payload, tenant)
                sid = getattr(stream, "stream_id", None)
                with self._lock:
                    self._inflight[idx] = stream
                while True:
                    try:
                        got.append(stream.read(timeout=30.0))
                    except ChannelClosed:
                        break
            except Exception:  # noqa: BLE001 - hard failover exhaustion
                with self._lock:
                    self.stream_errors += 1
                    self._inflight.pop(idx, None)
                    if sid in self._watched:
                        self._watched[sid] = "error"
                import time as _time

                _time.sleep(0.2)
                continue
            finally:
                if stream is not None:
                    stream.close()
            with self._lock:
                expected = self._expected_by_model.get(model)
            ok = expected is not None and got == expected
            if not ok:
                exp_len = len(expected) if expected is not None else -1
                with self._lock:
                    self.verify_failures.append(
                        f"stream under {model!r} returned {len(got)} "
                        f"tokens, expected {exp_len} (token-exact resume "
                        "broken or epochs mixed mid-stream)"
                    )
            else:
                self._emit_trajectory(seq, payload, got, model)
                with self._lock:
                    self.completed += 1
            with self._lock:
                self._inflight.pop(idx, None)
                if sid in self._watched:
                    self._watched[sid] = "ok" if ok else "verify_fail"

    def _emit_trajectory(
        self, seq: int, payload: dict, tokens: List[str], model: str
    ) -> None:
        from ray_tpu.rl.trajectory import Trajectory, encode_block

        traj = Trajectory(
            traj_id=f"stream:{seq}",
            prompt=[0],
            # token TEXTS hash to ids. crc32, not hash(): the builtin is
            # salted per process, and the loss-continuity oracle re-reads
            # these ids in other processes
            tokens=[0]
            + [
                zlib.crc32(t.encode("utf-8")) % self.token_space
                for t in tokens
            ],
            weights_epoch=model_epoch(model),
            rollout_id="serve",
            seed=int(payload.get("seed", 0)),
        )
        block = encode_block([traj])
        try:
            if hasattr(self.feed.emit, "remote"):
                ray_tpu.get(self.feed.emit.remote(block), timeout=30.0)
            else:
                self.feed.emit(block)
        except Exception:  # noqa: BLE001 - feed actor mid-restart
            pass

    # -- orchestrator rl_adapter surface ---------------------------------
    def pick_rollout_pid(self, rng) -> Optional[int]:
        return self.pick_replica_pid(rng)

    def trainer_gang_ids(self) -> List[str]:
        gid = getattr(self.trainer, "gang_id", None)
        return [gid] if gid else []

    def published_epoch(self) -> int:
        return int(self.publisher.current_epoch()["committed"])

    def replica_epochs(self) -> List[int]:
        """Published weights epoch each live replica currently serves
        (parsed from its engine's model id)."""
        rs = self.router._rs
        with rs.lock:
            replicas = [r for r in rs.replicas if not r.draining]
        out: List[int] = []
        for r in replicas:
            try:
                stats = ray_tpu.get(
                    r.actor.serve_stats.remote(), timeout=10.0
                )
            except Exception:  # noqa: BLE001 - dead replica: not "live"
                continue
            out.append(model_epoch(stats.get("model_id")))
        return out

    def trajectory_accounting(self) -> Dict[str, int]:
        if hasattr(self.feed.accounting, "remote"):
            return ray_tpu.get(self.feed.accounting.remote(), timeout=30.0)
        return self.feed.accounting()

    # -- publish-hold kill window ----------------------------------------
    def _between_phases(self, epoch: int) -> None:
        if not self._hold_requested.is_set():
            return
        self._in_window.set()
        self._release.wait(timeout=60.0)

    def arm_publish_hold(self, timeout: float = 20.0) -> bool:
        """Latch the hold and wait for the next publish to park inside
        its seal->commit window. False if none arrives in time."""
        self._release.clear()
        self._in_window.clear()
        self._hold_requested.set()
        armed = self._in_window.wait(timeout)
        if not armed:
            self._hold_requested.clear()
        return armed

    def release_publish_hold(self) -> None:
        self._hold_requested.clear()
        self._release.set()
