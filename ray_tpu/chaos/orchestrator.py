"""Deterministic chaos orchestrator.

Executes a seeded :class:`~ray_tpu.chaos.plan.ChaosPlan` against a live
:class:`~ray_tpu.cluster.cluster_utils.Cluster`, interleaving faults with
a verifiable workload and asserting invariant convergence after every
injection. Fault kinds:

- ``kill_node``      — SIGKILL an agent process; a replacement node joins
                       so capacity (and actor restart targets) survive a
                       long soak.
- ``head_restart``   — restart the head mid-flight on the same port
                       (requires a persist_path so durable state recovers).
- ``partition``      — per-peer RPC blackhole from the control plane to
                       one node for a bounded hold, then heal. Long holds
                       open the circuit breaker into the health path;
                       short holds exercise retry/spillback only.
- ``straggler``      — delay ramp on one node's RPC path (injected
                       latency rises, holds, falls back to zero).
- ``object_drop``    — destroy every stored copy of an acked object and
                       drop its directory entries; lineage must rebuild
                       it.

- ``owner_kill``      — SIGKILL a sacrificial OWNER process (a real
                       driver in its own process, ``owner_proc.py``);
                       the head must notice purely through missed owner
                       heartbeats and reap its actors/leases/objects
                       with nothing leaked.
- ``zygote_kill``     — SIGKILL one node's fork-server (taking its
                       forked workers with it); worker spawns must keep
                       succeeding (zygote restart or cold spawn).
- ``replica_kill``    — SIGKILL a serving replica's worker mid-stream
                       (requires a registered ``serve_adapter``);
                       in-flight streams must fail over with no
                       duplicated/dropped acked tokens and the replica
                       set must backfill to its desired count.
- ``prefill_kill``    — SIGKILL a PREFILL-tier worker of a disaggregated
                       serving deployment mid-KV-handoff; decode
                       replicas must fall back to local re-prefill
                       (token-exact — generation is seed-deterministic),
                       streams keep completing, and the prefill tier
                       backfills to its desired count.
- ``rank_node_kill``  — SIGKILL a node hosting elastic training gang
                       ranks (picked from the head's gang table); the
                       gang must fence its epoch, reshape to the
                       surviving topology, and resume from object-plane
                       seals with no disk restore.

Every fault records recovery latency = time from injection until all
invariants are green again; the run result carries p50/p95 plus objects
reconstructed and the post-soak arena zombie count, for the bench chaos
tier.
"""
from __future__ import annotations

import json
import logging
import os
import random
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Histogram as _Histogram

from .invariants import InvariantChecker
from .plan import ChaosPlan, FaultSpec
from .workload import ChaosWorkload

logger = logging.getLogger("ray_tpu.chaos")

CHAOS_FAULTS = _Counter(
    "chaos_faults_injected_total",
    "Faults injected by the chaos orchestrator.",
    label_names=("kind",),
)
CHAOS_INVARIANT_FAILURES = _Counter(
    "chaos_invariant_failures_total",
    "Invariant checks that failed after a fault converged.",
    label_names=("kind",),
)
CHAOS_RECOVERY = _Histogram(
    "chaos_recovery_seconds",
    "Time from fault injection to all invariants green.",
)


@dataclass
class FaultResult:
    spec: FaultSpec
    ok: bool
    recovery_s: float
    failures: List[str] = field(default_factory=list)
    detail: str = ""


@dataclass
class ChaosRunResult:
    seed: int
    faults: List[FaultResult] = field(default_factory=list)
    objects_reconstructed: int = 0
    objects_acked: int = 0
    # deleted-with-outstanding-pins arena entries still alive after the
    # soak settled: any nonzero value is a reader-pin leak
    arena_zombies_after: int = 0
    owners_killed: int = 0

    @property
    def ok(self) -> bool:
        return all(f.ok for f in self.faults)

    def recovery_percentiles(self) -> Dict[str, float]:
        lat = sorted(f.recovery_s for f in self.faults)
        if not lat:
            return {"p50": 0.0, "p95": 0.0}
        return {
            "p50": lat[len(lat) // 2],
            "p95": lat[min(len(lat) - 1, int(len(lat) * 0.95))],
        }

    def summary(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.faults:
            counts[f.spec.kind] = counts.get(f.spec.kind, 0) + 1
        return {
            "seed": self.seed,
            "ok": self.ok,
            "faults_injected": len(self.faults),
            "fault_counts": counts,
            "objects_acked": self.objects_acked,
            "objects_reconstructed": self.objects_reconstructed,
            "arena_zombies_after": self.arena_zombies_after,
            "owners_killed": self.owners_killed,
            "recovery_latency_s": self.recovery_percentiles(),
            "failures": [
                {"fault": f.spec.index, "kind": f.spec.kind, "why": f.failures}
                for f in self.faults
                if not f.ok
            ],
        }


class ChaosOrchestrator:
    def __init__(
        self,
        cluster,
        workload: ChaosWorkload,
        plan: ChaosPlan,
        *,
        node_resources: Optional[dict] = None,
        workers_per_node: int = 2,
        tasks_per_step: int = 4,
        partition_hold_s: float = 1.0,
        straggler_peak_s: float = 0.3,
        convergence_budget_s: float = 60.0,
        serve_adapter=None,
        rl_adapter=None,
    ):
        self.cluster = cluster
        self.workload = workload
        self.plan = plan
        self.node_resources = dict(node_resources or {"CPU": 2.0})
        self.workers_per_node = workers_per_node
        self.tasks_per_step = tasks_per_step
        self.partition_hold_s = partition_hold_s
        self.straggler_peak_s = straggler_peak_s
        self.convergence_budget_s = float(convergence_budget_s)
        self.checker = InvariantChecker(
            cluster,
            workload,
            actor_restart_budget_s=convergence_budget_s,
            object_timeout_s=convergence_budget_s,
        )
        # runtime randomness (victim picks among equivalent live nodes)
        # derives from the plan seed too: full-run determinism modulo
        # scheduler placement
        self._rng = random.Random(plan.seed ^ 0x5EED)
        # sacrificial owner process (owner_kill): pre-spawned so the kill
        # never pays setup latency inside a fault's recovery window
        self._owner_proc: Optional[subprocess.Popen] = None
        self._owner_info_path: Optional[str] = None
        self._killed_owner: Optional[dict] = None
        # serving-plane adapter (chaos/serve.py ServeStreamWorkload):
        # victim selection + stream/replica invariants for replica_kill
        self.serve_adapter = serve_adapter
        self._killed_replica: Optional[int] = None
        self._killed_prefill: Optional[int] = None
        # online-RL adapter (ISSUE 20): rollout victim selection, the
        # publish-hold kill window, and the epoch/accounting invariants
        self.rl_adapter = rl_adapter
        self._killed_rollout: Optional[int] = None
        self._killed_trainer_gangs: Optional[Dict[str, int]] = None
        self._head_killed_mid_publish = False

    # -- sacrificial owner ----------------------------------------------
    def _spawn_owner_proc(self) -> None:
        """Start (or replace) the sacrificial owner driver, async — the
        info file appears once its actors are ALIVE."""
        if self._owner_proc is not None:
            # replacing after an owner_kill: reap the corpse and drop its
            # info file, or a long soak leaks one of each per kill
            self._stop_owner_proc()
        fd, path = tempfile.mkstemp(prefix="ray_tpu_chaos_owner_")
        os.close(fd)
        os.unlink(path)  # owner_proc writes it atomically when ready
        self._owner_info_path = path
        self._owner_proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.chaos.owner_proc",
                "--head",
                self.cluster.address,
                "--info-file",
                path,
                "--actors",
                "1",
            ]
        )

    def _owner_info(self) -> Optional[dict]:
        if self._owner_info_path is None:
            return None
        try:
            with open(self._owner_info_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _stop_owner_proc(self) -> None:
        if self._owner_proc is not None and self._owner_proc.poll() is None:
            self._owner_proc.kill()
            self._owner_proc.wait(timeout=10)
        if self._owner_info_path:
            try:
                os.unlink(self._owner_info_path)
            except OSError:
                pass

    # -- node selection -------------------------------------------------
    def _live_nodes(self) -> List[str]:
        return sorted(
            nid
            for nid, info in self.cluster.head.nodes.items()
            if info.alive
            and self.cluster._agents.get(nid) is not None
            and self.cluster._agents[nid].poll() is None
        )

    def _pick_node(self, spec: FaultSpec) -> Optional[str]:
        live = self._live_nodes()
        if not live:
            return None
        return live[spec.target % len(live)]

    # -- fault injection ------------------------------------------------
    def _inject(self, spec: FaultSpec) -> str:
        kind = spec.kind
        CHAOS_FAULTS.inc(labels={"kind": kind})
        if kind == "kill_node":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to kill"
            self.cluster.kill_node(nid)
            # backfill so capacity and restart targets survive the soak
            self.cluster.add_node(
                dict(self.node_resources),
                num_workers=self.workers_per_node,
                wait=False,
            )
            return f"killed {nid}, replacement joining"
        if kind == "head_restart":
            if not self.cluster._persist_path:
                return "skipped: no persist_path (head restart needs one)"
            self.cluster.restart_head()
            return "head restarted on the same port"
        if kind == "head_kill_promote":
            standby = getattr(self.cluster, "standby", None)
            if standby is None or standby.promoted is not None:
                return "skipped: no armed warm standby"
            self._pre_kill_epoch = self.cluster.head.cluster_epoch
            self._head_killed = True
            self.cluster.kill_head()
            if not standby.auto_promote:
                self.cluster.promote()
            return (
                "SIGKILLed the leader (epoch "
                f"{self._pre_kill_epoch}); standby promoting"
            )
        if kind == "partition":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to partition"
            hold = self.partition_hold_s * (0.5 + spec.magnitude)
            self.cluster.partition_node(nid)
            time.sleep(hold)
            self.cluster.heal_node(nid)
            return f"partitioned {nid} for {hold:.2f}s"
        if kind == "straggler":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to slow down"
            peak = self.straggler_peak_s * (0.5 + spec.magnitude)
            # ramp up, hold, ramp down — a drifting slow node, not a cliff
            for frac in (0.33, 0.66, 1.0):
                self.cluster.set_node_delay(nid, peak * frac)
                time.sleep(0.1)
            time.sleep(0.2)
            self.cluster.set_node_delay(nid, 0.0)
            return f"straggler ramp on {nid} peaking at {peak:.2f}s"
        if kind == "object_drop":
            ref = self.workload.sample_acked_ref(self._rng)
            if ref is None:
                return "skipped: nothing acked to drop yet"
            if not self.cluster.head.chaos_drop_object(ref.hex):
                return f"skipped: {ref.hex[:8]} not droppable (inline?)"
            self._dropped_hex = ref.hex
            return f"dropped all copies of {ref.hex[:8]}"
        if kind == "owner_kill":
            proc = self._owner_proc
            info = self._owner_info()
            if proc is None or proc.poll() is not None or info is None:
                return "skipped: sacrificial owner not ready yet"
            proc.kill()  # SIGKILL: no DisconnectClient, no atexit
            proc.wait(timeout=10)
            self._killed_owner = info
            return (
                f"SIGKILLed owner {info['client_id'][:8]} "
                f"(pid {info['pid']}, {len(info['actor_ids'])} actors)"
            )
        if kind == "replica_kill":
            if self.serve_adapter is None:
                return "skipped: no serve workload registered"
            pid = self.serve_adapter.pick_replica_pid(self._rng)
            if pid is None:
                return "skipped: no live replica to kill"
            import signal as _signal

            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                return f"skipped: replica pid {pid} already gone"
            self._killed_replica = pid
            return f"SIGKILLed serve replica worker pid {pid}"
        if kind == "prefill_kill":
            # SIGKILL a prefill-tier worker mid-KV-handoff: any handoff
            # it was sealing dies with it, so decode replicas must fall
            # back to local re-prefill (seed-deterministic, hence
            # token-exact) and the router keeps admitting while the
            # prefill tier backfills
            if self.serve_adapter is None:
                return "skipped: no serve workload registered"
            pick = getattr(self.serve_adapter, "pick_prefill_pid", None)
            if pick is None:
                return "skipped: serve workload has no prefill tier"
            pid = pick(self._rng)
            if pid is None:
                return "skipped: no live prefill worker to kill"
            import signal as _signal

            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                return f"skipped: prefill pid {pid} already gone"
            self._killed_prefill = pid
            return f"SIGKILLed prefill worker pid {pid} mid-handoff"
        if kind == "router_kill":
            # abruptly kill one ingress router of the fleet: its push
            # endpoint vanishes and its in-flight streams FAIL; the
            # siblings inheriting the hash ranges must resume every one
            # token-exact from the replicated delivered checkpoints
            if self.serve_adapter is None:
                return "skipped: no serve workload registered"
            kill = getattr(self.serve_adapter, "kill_router", None)
            if kill is None:
                return "skipped: serve workload is not fleet-aware"
            rid = kill(self._rng)
            if rid is None:
                return "skipped: no killable router (fleet of one?)"
            self._killed_router = rid
            return f"killed ingress router {rid} mid-stream"
        if kind == "peer_conn_drop":
            # sever every data socket one node is SERVING mid-transfer:
            # pullers' in-flight stripes fail and must RESUME (only the
            # lost stripes re-fetch — zero acked loss, no duplicate
            # bytes), which the invariant checker asserts afterwards
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node"
            addr = self.cluster.agent_address(nid)
            if addr is None:
                return "skipped: node has no address"
            from ray_tpu.cluster.rpc import RpcClient, RpcError

            client = RpcClient(addr)
            try:
                reply = client.call("ChaosDropPeerConn", timeout=10.0)
            except RpcError:
                return f"skipped: agent {nid} unreachable"
            finally:
                client.close()
            return (
                f"severed {reply.get('dropped', 0)} data socket(s) "
                f"served by {nid}"
            )
        if kind == "rank_node_kill":
            # SIGKILL a node hosting elastic gang ranks, chosen from the
            # head's gang table: the gang-epoch protocol must fence the
            # dead generation, reshape to the surviving topology, and
            # resume from object-plane seals (no disk restore)
            head = self.cluster.head
            with head._lock:
                gangs = {
                    gid: {
                        "epoch": g["epoch"],
                        "members": dict(g["members"]),
                    }
                    for gid, g in head._gangs.items()
                }
            live = set(self._live_nodes())
            hosts = sorted(
                {
                    n
                    for g in gangs.values()
                    for n in g["members"].values()
                    if n in live
                }
            )
            if not hosts:
                return "skipped: no live node hosts an elastic gang rank"
            nid = hosts[spec.target % len(hosts)]
            self._killed_gang_nodes = {
                gid: g["epoch"]
                for gid, g in gangs.items()
                if nid in g["members"].values()
            }
            self.cluster.kill_node(nid)
            # backfill so the gang can grow back during the soak
            self.cluster.add_node(
                dict(self.node_resources),
                num_workers=self.workers_per_node,
                wait=False,
            )
            return (
                f"SIGKILLed rank node {nid} "
                f"({len(self._killed_gang_nodes)} gang(s) fencing)"
            )
        if kind == "node_drain":
            # cooperative drain-ahead retire (PR 19): head zeroes the
            # node's advertised capacity, preemptively migrates leased
            # work off it before the deadline, then terminates the agent.
            # Unlike kill_node the work is moved, not lost — retryable
            # leases must land elsewhere with zero attempts burned.
            live = self._live_nodes()
            if len(live) < 2:
                return "skipped: need >=2 live nodes to drain one"
            nid = live[spec.target % len(live)]
            drain = getattr(self.cluster, "drain_node", None)
            if drain is None:
                return "skipped: cluster has no drain support"
            deadline = 5.0 + 10.0 * spec.magnitude
            drained = drain(nid, deadline_s=deadline)
            # backfill so capacity survives the soak
            self.cluster.add_node(
                dict(self.node_resources),
                num_workers=self.workers_per_node,
                wait=False,
            )
            return (
                f"drained {nid} ({'clean' if drained else 'deadline'}) "
                f"within {deadline:.1f}s, replacement joining"
            )
        if kind == "rollout_kill":
            # SIGKILL a rollout replica mid-trajectory: its in-flight
            # streams fail over token-exact via resume_from, and the
            # re-emitted trajectories dedup by id in the feed — the
            # accounting invariant stays balanced
            if self.rl_adapter is None:
                return "skipped: no RL workload registered"
            pid = self.rl_adapter.pick_rollout_pid(self._rng)
            if pid is None:
                return "skipped: no live rollout replica to kill"
            import signal as _signal

            try:
                os.kill(pid, _signal.SIGKILL)
            except OSError:
                return f"skipped: rollout pid {pid} already gone"
            self._killed_rollout = pid
            return f"SIGKILLed rollout replica pid {pid} mid-trajectory"
        if kind == "trainer_rank_kill":
            # SIGKILL a node hosting ranks of the RL TRAINER's gang
            # mid-step: the gang reshapes (PR 14) and the replayed step
            # pulls the identical batch from the feed's step cache, so
            # the loss curve stays continuous vs the unkilled reference
            if self.rl_adapter is None:
                return "skipped: no RL workload registered"
            gang_ids = set(self.rl_adapter.trainer_gang_ids())
            if not gang_ids:
                return "skipped: RL trainer gang not registered yet"
            head = self.cluster.head
            with head._lock:
                gangs = {
                    gid: {
                        "epoch": g["epoch"],
                        "members": dict(g["members"]),
                    }
                    for gid, g in head._gangs.items()
                    if gid in gang_ids
                }
            live = set(self._live_nodes())
            hosts = sorted(
                {
                    n
                    for g in gangs.values()
                    for n in g["members"].values()
                    if n in live
                }
            )
            if not hosts:
                return "skipped: no live node hosts an RL trainer rank"
            nid = hosts[spec.target % len(hosts)]
            self._killed_trainer_gangs = {
                gid: g["epoch"]
                for gid, g in gangs.items()
                if nid in g["members"].values()
            }
            self.cluster.kill_node(nid)
            self.cluster.add_node(
                dict(self.node_resources),
                num_workers=self.workers_per_node,
                wait=False,
            )
            return (
                f"SIGKILLed RL trainer rank node {nid} "
                f"({len(self._killed_trainer_gangs)} gang(s) fencing)"
            )
        if kind == "head_kill_mid_publish":
            # kill the leader INSIDE the seal->commit window of a
            # two-phase weights publish: the adapter holds the publisher
            # between phases, we SIGKILL the head there, the standby
            # promotes, and the release lets the publisher's retry land
            # against the new leader — either the old or the new epoch
            # becomes visible, never a torn in-between
            if self.rl_adapter is None:
                return "skipped: no RL workload registered"
            standby = getattr(self.cluster, "standby", None)
            if standby is None or standby.promoted is not None:
                return "skipped: no armed warm standby"
            # how long a publish cycle can take under chaos scales with
            # the same recovery envelope the convergence budget models —
            # a fixed small window skips the fault whenever the trainer
            # is mid-recovery from an earlier kill
            arm_s = min(60.0, max(20.0, self.convergence_budget_s / 3.0))
            if not self.rl_adapter.arm_publish_hold(timeout=arm_s):
                return "skipped: no publish entered the seal window"
            try:
                self._pre_kill_epoch = self.cluster.head.cluster_epoch
                self._head_killed = True
                self._head_killed_mid_publish = True
                self.cluster.kill_head()
                if not standby.auto_promote:
                    self.cluster.promote()
            finally:
                self.rl_adapter.release_publish_hold()
            return (
                "SIGKILLed the leader inside a seal->commit window "
                f"(epoch {self._pre_kill_epoch}); standby promoting"
            )
        if kind == "zygote_kill":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node"
            addr = self.cluster.agent_address(nid)
            if addr is None:
                return "skipped: node has no address"
            from ray_tpu.cluster.rpc import RpcClient, RpcError

            client = RpcClient(addr)
            try:
                reply = client.call("ChaosKillZygote", timeout=10.0)
            except RpcError:
                return f"skipped: agent {nid} unreachable"
            finally:
                client.close()
            if not reply.get("killed"):
                return f"skipped: {reply.get('reason')}"
            return f"killed zygote pid {reply['pid']} on {nid}"
        raise ValueError(f"unknown fault kind {kind!r}")

    # -- the run --------------------------------------------------------
    def run(self) -> ChaosRunResult:
        result = ChaosRunResult(seed=self.plan.seed)
        if any(f.kind == "owner_kill" for f in self.plan.faults):
            self._spawn_owner_proc()
        try:
            for spec in self.plan.faults:
                self.workload.step(self.tasks_per_step)
                time.sleep(spec.delay_s)
                pre = self.checker.snapshot()
                t0 = time.monotonic()
                self._dropped_hex: Optional[str] = None
                self._killed_owner = None
                self._killed_replica = None
                self._killed_prefill = None
                self._killed_router: Optional[str] = None
                self._killed_gang_nodes: Optional[Dict[str, int]] = None
                self._head_killed = False
                self._pre_kill_epoch = 0
                self._killed_rollout = None
                self._killed_trainer_gangs = None
                self._head_killed_mid_publish = False
                detail = self._inject(spec)
                logger.info(
                    "chaos #%d %s: %s", spec.index, spec.kind, detail
                )
                if not self._head_killed:
                    # flight recorder (ISSUE 15): snapshot the head's
                    # events/spans/metrics while the fault is fresh
                    # (head faults dump from the promotion path instead
                    # — this head is the corpse)
                    try:
                        self.cluster.head._dump_crash_bundle(
                            f"chaos-{spec.kind}"
                        )
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                promote_failures: List[str] = []
                if self._head_killed:
                    # the promotion must land BEFORE the generic
                    # convergence pass (which reads cluster.head): epoch
                    # strictly increased, exactly one unfenced leader,
                    # then every in-flight wave started before the kill
                    # completes with zero acked loss
                    promote_failures = self.checker.wait_standby_promoted(
                        self._pre_kill_epoch,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    promote_failures += self.checker.wait_inflight_survive(
                        self.serve_adapter,
                        timeout=self.checker.object_timeout_s,
                    )
                check = self.checker.check_convergence(pre)
                if promote_failures:
                    check.ok = False
                    check.failures = promote_failures + check.failures
                if self._head_killed:
                    # re-arm a fresh standby so later faults in the soak
                    # can fail over again (the promoted one is consumed)
                    standby = self.cluster.standby
                    try:
                        self.cluster.start_standby(
                            auto_promote=(
                                standby.auto_promote
                                if standby is not None
                                else True
                            )
                        )
                    except Exception:  # noqa: BLE001 - judged above
                        logger.exception("could not re-arm a standby")
                if self._dropped_hex is not None:
                    # the drop's specific victim must rebuild (the sampled
                    # acked sweep may not have included it)
                    miss = self.workload.verify_ref(
                        self._dropped_hex,
                        timeout=self.checker.object_timeout_s,
                    )
                    if miss:
                        check.ok = False
                        check.failures.append(miss)
                if self._killed_owner is not None:
                    # nothing of the dead owner's may outlive the liveness
                    # window: no ALIVE actors, no lease rows, no session
                    result.owners_killed += 1
                    owner_fail = self.checker.wait_owner_reaped(
                        self._killed_owner["client_id"],
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if owner_fail:
                        check.ok = False
                        check.failures.extend(owner_fail)
                    # pre-warm the next sacrificial owner off the clock
                    self._spawn_owner_proc()
                if self._killed_gang_nodes:
                    # elastic-training invariant: every gang that had a
                    # rank on the corpse advances its epoch (the dead
                    # generation is fenced) and re-registers a healthy
                    # membership — or finishes and unregisters
                    gang_fail = self.checker.wait_gang_reshaped(
                        self._killed_gang_nodes,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if gang_fail:
                        check.ok = False
                        check.failures.extend(gang_fail)
                if self._killed_replica is not None:
                    # serving invariants: in-flight streams fail over or
                    # restart with no duplicated/dropped acked tokens,
                    # and the replica set backfills to its target
                    serve_fail = self.checker.wait_streams_resume(
                        self.serve_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    serve_fail += self.checker.wait_replica_backfilled(
                        self.serve_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if serve_fail:
                        check.ok = False
                        check.failures.extend(serve_fail)
                if self._killed_prefill is not None:
                    # disaggregated-serving invariants: streams keep
                    # completing token-exact (decode falls back to local
                    # re-prefill when the handoff producer died) and the
                    # prefill tier backfills to its desired count
                    pre_fail = self.checker.wait_streams_resume(
                        self.serve_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    pre_fail += self.checker.wait_prefill_backfilled(
                        self.serve_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if pre_fail:
                        check.ok = False
                        check.failures.extend(pre_fail)
                if self._killed_router is not None:
                    # router-fleet invariant: every stream that was in
                    # flight on the corpse completes token-exact on a
                    # sibling (zero duplicated/dropped acked deltas),
                    # and fresh streams keep completing after the kill
                    fleet_fail = (
                        self.checker.wait_streams_resume_cross_router(
                            self.serve_adapter,
                            timeout=self.checker.actor_restart_budget_s,
                        )
                    )
                    if fleet_fail:
                        check.ok = False
                        check.failures.extend(fleet_fail)
                if self._killed_rollout is not None:
                    # online-RL rollout death: in-flight streams resume
                    # token-exact, the replica set backfills, the fleet
                    # reconverges on one weights epoch, and no
                    # trajectory goes unaccounted (resume re-emits dedup)
                    rl_fail = self.checker.wait_streams_resume(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    rl_fail += self.checker.wait_replica_backfilled(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    rl_fail += self.checker.wait_weights_epoch_converged(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    rl_fail += self.checker.wait_trajectory_accounting(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if rl_fail:
                        check.ok = False
                        check.failures.extend(rl_fail)
                if self._killed_trainer_gangs:
                    # RL trainer rank death: the gang fences + reshapes,
                    # and the conservation law still balances (replayed
                    # steps re-read the cached batch, nothing double-
                    # counts)
                    rl_fail = self.checker.wait_gang_reshaped(
                        self._killed_trainer_gangs,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    rl_fail += self.checker.wait_trajectory_accounting(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if rl_fail:
                        check.ok = False
                        check.failures.extend(rl_fail)
                if self._head_killed_mid_publish:
                    # publish atomicity across the promotion: the
                    # publisher's retry resolved to exactly one epoch on
                    # the new leader and the fleet converged on it
                    rl_fail = self.checker.wait_weights_epoch_converged(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    rl_fail += self.checker.wait_trajectory_accounting(
                        self.rl_adapter,
                        timeout=self.checker.actor_restart_budget_s,
                    )
                    if rl_fail:
                        check.ok = False
                        check.failures.extend(rl_fail)
                recovery = time.monotonic() - t0
                CHAOS_RECOVERY.observe(recovery)
                if not check.ok:
                    CHAOS_INVARIANT_FAILURES.inc(
                        len(check.failures), labels={"kind": spec.kind}
                    )
                    logger.error(
                        "chaos #%d %s invariants FAILED (seed=%d): %s",
                        spec.index,
                        spec.kind,
                        self.plan.seed,
                        check.failures,
                    )
                if (
                    spec.kind == "object_drop"
                    and detail.startswith("dropped")
                    and check.ok
                ):
                    # every copy was destroyed and the invariant pass
                    # re-got the value: lineage rebuilt exactly one object
                    result.objects_reconstructed += 1
                result.faults.append(
                    FaultResult(
                        spec=spec,
                        ok=check.ok,
                        recovery_s=recovery,
                        failures=check.failures,
                        detail=detail,
                    )
                )
        finally:
            self.cluster.heal_all()
            self._stop_owner_proc()
        result.objects_acked = self.workload.objects_acked
        # post-soak leak audit: every reader released (or died and had its
        # pin log replayed) — deleted-with-pins entries must be zero. A
        # short settle loop tolerates frees still in flight.
        result.arena_zombies_after = self.checker.wait_arena_zombies_zero(
            timeout=15.0
        )
        return result
