"""Deterministic chaos orchestrator.

Executes a seeded :class:`~ray_tpu.chaos.plan.ChaosPlan` against a live
:class:`~ray_tpu.cluster.cluster_utils.Cluster`, interleaving faults with
a verifiable workload and asserting invariant convergence after every
injection. Fault kinds:

- ``kill_node``      — SIGKILL an agent process; a replacement node joins
                       so capacity (and actor restart targets) survive a
                       long soak.
- ``head_restart``   — restart the head mid-flight on the same port
                       (requires a persist_path so durable state recovers).
- ``partition``      — per-peer RPC blackhole from the control plane to
                       one node for a bounded hold, then heal. Long holds
                       open the circuit breaker into the health path;
                       short holds exercise retry/spillback only.
- ``straggler``      — delay ramp on one node's RPC path (injected
                       latency rises, holds, falls back to zero).
- ``object_drop``    — destroy every stored copy of an acked object and
                       drop its directory entries; lineage must rebuild
                       it.

Every fault records recovery latency = time from injection until all
invariants are green again; the run result carries p50/p95 plus objects
reconstructed, for the bench chaos tier.
"""
from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Histogram as _Histogram

from .invariants import InvariantChecker
from .plan import ChaosPlan, FaultSpec
from .workload import ChaosWorkload

logger = logging.getLogger("ray_tpu.chaos")

CHAOS_FAULTS = _Counter(
    "chaos_faults_injected_total",
    "Faults injected by the chaos orchestrator.",
    label_names=("kind",),
)
CHAOS_INVARIANT_FAILURES = _Counter(
    "chaos_invariant_failures_total",
    "Invariant checks that failed after a fault converged.",
    label_names=("kind",),
)
CHAOS_RECOVERY = _Histogram(
    "chaos_recovery_seconds",
    "Time from fault injection to all invariants green.",
)


@dataclass
class FaultResult:
    spec: FaultSpec
    ok: bool
    recovery_s: float
    failures: List[str] = field(default_factory=list)
    detail: str = ""


@dataclass
class ChaosRunResult:
    seed: int
    faults: List[FaultResult] = field(default_factory=list)
    objects_reconstructed: int = 0
    objects_acked: int = 0

    @property
    def ok(self) -> bool:
        return all(f.ok for f in self.faults)

    def recovery_percentiles(self) -> Dict[str, float]:
        lat = sorted(f.recovery_s for f in self.faults)
        if not lat:
            return {"p50": 0.0, "p95": 0.0}
        return {
            "p50": lat[len(lat) // 2],
            "p95": lat[min(len(lat) - 1, int(len(lat) * 0.95))],
        }

    def summary(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.faults:
            counts[f.spec.kind] = counts.get(f.spec.kind, 0) + 1
        return {
            "seed": self.seed,
            "ok": self.ok,
            "faults_injected": len(self.faults),
            "fault_counts": counts,
            "objects_acked": self.objects_acked,
            "objects_reconstructed": self.objects_reconstructed,
            "recovery_latency_s": self.recovery_percentiles(),
            "failures": [
                {"fault": f.spec.index, "kind": f.spec.kind, "why": f.failures}
                for f in self.faults
                if not f.ok
            ],
        }


class ChaosOrchestrator:
    def __init__(
        self,
        cluster,
        workload: ChaosWorkload,
        plan: ChaosPlan,
        *,
        node_resources: Optional[dict] = None,
        workers_per_node: int = 2,
        tasks_per_step: int = 4,
        partition_hold_s: float = 1.0,
        straggler_peak_s: float = 0.3,
        convergence_budget_s: float = 60.0,
    ):
        self.cluster = cluster
        self.workload = workload
        self.plan = plan
        self.node_resources = dict(node_resources or {"CPU": 2.0})
        self.workers_per_node = workers_per_node
        self.tasks_per_step = tasks_per_step
        self.partition_hold_s = partition_hold_s
        self.straggler_peak_s = straggler_peak_s
        self.checker = InvariantChecker(
            cluster,
            workload,
            actor_restart_budget_s=convergence_budget_s,
            object_timeout_s=convergence_budget_s,
        )
        # runtime randomness (victim picks among equivalent live nodes)
        # derives from the plan seed too: full-run determinism modulo
        # scheduler placement
        self._rng = random.Random(plan.seed ^ 0x5EED)

    # -- node selection -------------------------------------------------
    def _live_nodes(self) -> List[str]:
        return sorted(
            nid
            for nid, info in self.cluster.head.nodes.items()
            if info.alive
            and self.cluster._agents.get(nid) is not None
            and self.cluster._agents[nid].poll() is None
        )

    def _pick_node(self, spec: FaultSpec) -> Optional[str]:
        live = self._live_nodes()
        if not live:
            return None
        return live[spec.target % len(live)]

    # -- fault injection ------------------------------------------------
    def _inject(self, spec: FaultSpec) -> str:
        kind = spec.kind
        CHAOS_FAULTS.inc(labels={"kind": kind})
        if kind == "kill_node":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to kill"
            self.cluster.kill_node(nid)
            # backfill so capacity and restart targets survive the soak
            self.cluster.add_node(
                dict(self.node_resources),
                num_workers=self.workers_per_node,
                wait=False,
            )
            return f"killed {nid}, replacement joining"
        if kind == "head_restart":
            if not self.cluster._persist_path:
                return "skipped: no persist_path (head restart needs one)"
            self.cluster.restart_head()
            return "head restarted on the same port"
        if kind == "partition":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to partition"
            hold = self.partition_hold_s * (0.5 + spec.magnitude)
            self.cluster.partition_node(nid)
            time.sleep(hold)
            self.cluster.heal_node(nid)
            return f"partitioned {nid} for {hold:.2f}s"
        if kind == "straggler":
            nid = self._pick_node(spec)
            if nid is None:
                return "skipped: no live node to slow down"
            peak = self.straggler_peak_s * (0.5 + spec.magnitude)
            # ramp up, hold, ramp down — a drifting slow node, not a cliff
            for frac in (0.33, 0.66, 1.0):
                self.cluster.set_node_delay(nid, peak * frac)
                time.sleep(0.1)
            time.sleep(0.2)
            self.cluster.set_node_delay(nid, 0.0)
            return f"straggler ramp on {nid} peaking at {peak:.2f}s"
        if kind == "object_drop":
            ref = self.workload.sample_acked_ref(self._rng)
            if ref is None:
                return "skipped: nothing acked to drop yet"
            if not self.cluster.head.chaos_drop_object(ref.hex):
                return f"skipped: {ref.hex[:8]} not droppable (inline?)"
            self._dropped_hex = ref.hex
            return f"dropped all copies of {ref.hex[:8]}"
        raise ValueError(f"unknown fault kind {kind!r}")

    # -- the run --------------------------------------------------------
    def run(self) -> ChaosRunResult:
        result = ChaosRunResult(seed=self.plan.seed)
        try:
            for spec in self.plan.faults:
                self.workload.step(self.tasks_per_step)
                time.sleep(spec.delay_s)
                pre = self.checker.snapshot()
                t0 = time.monotonic()
                self._dropped_hex: Optional[str] = None
                detail = self._inject(spec)
                logger.info(
                    "chaos #%d %s: %s", spec.index, spec.kind, detail
                )
                check = self.checker.check_convergence(pre)
                if self._dropped_hex is not None:
                    # the drop's specific victim must rebuild (the sampled
                    # acked sweep may not have included it)
                    miss = self.workload.verify_ref(
                        self._dropped_hex,
                        timeout=self.checker.object_timeout_s,
                    )
                    if miss:
                        check.ok = False
                        check.failures.append(miss)
                recovery = time.monotonic() - t0
                CHAOS_RECOVERY.observe(recovery)
                if not check.ok:
                    CHAOS_INVARIANT_FAILURES.inc(
                        len(check.failures), labels={"kind": spec.kind}
                    )
                    logger.error(
                        "chaos #%d %s invariants FAILED (seed=%d): %s",
                        spec.index,
                        spec.kind,
                        self.plan.seed,
                        check.failures,
                    )
                if (
                    spec.kind == "object_drop"
                    and detail.startswith("dropped")
                    and check.ok
                ):
                    # every copy was destroyed and the invariant pass
                    # re-got the value: lineage rebuilt exactly one object
                    result.objects_reconstructed += 1
                result.faults.append(
                    FaultResult(
                        spec=spec,
                        ok=check.ok,
                        recovery_s=recovery,
                        failures=check.failures,
                        detail=detail,
                    )
                )
        finally:
            self.cluster.heal_all()
        result.objects_acked = self.workload.objects_acked
        return result
