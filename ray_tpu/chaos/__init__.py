"""Deterministic chaos engineering for the distributed runtime.

A seeded fault plan (kills, head restarts, partitions, stragglers,
object drops) executes against a live cluster interleaved with a
verifiable workload; an invariant checker asserts convergence after
every fault. The same seed replays the exact same schedule
(``RAY_TPU_CHAOS_SEED``); see chaos/plan.py.
"""
from ray_tpu.config import cfg

from .invariants import CheckResult, InvariantChecker, Snapshot  # noqa: F401
from .orchestrator import (  # noqa: F401
    ChaosOrchestrator,
    ChaosRunResult,
    FaultResult,
)
from .plan import (  # noqa: F401
    DEFAULT_MIX,
    FAILOVER_MIX,
    KINDS,
    NET_MIX,
    RL_MIX,
    ROUTER_MIX,
    SERVE_MIX,
    ChaosPlan,
    FaultSpec,
    make_plan,
)
from .rl import RLRolloutWorkload  # noqa: F401
from .serve import ServeStreamWorkload  # noqa: F401
from .workload import ChaosCounter, ChaosWorkload  # noqa: F401


def chaos_seed(default: int = 0) -> int:
    """The run's chaos seed: ``RAY_TPU_CHAOS_SEED`` env (via config) or
    ``default``. Print it in any failure report — it replays the exact
    fault schedule."""
    env = cfg.chaos_seed
    return int(env) if env else int(default)
