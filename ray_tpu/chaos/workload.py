"""The workload chaos runs against.

Deterministic, verifiable traffic: plain tasks produce payloads larger
than ``inline_object_max`` (so their only copies live in node stores and
faults genuinely threaten them), a named restartable actor absorbs
method calls, and every acked result's expected bytes are recomputable
client-side. "Acked" means a ``get()`` returned the value at least once
— the invariant the orchestrator enforces is that an acked object is
NEVER lost afterwards (lineage rebuilds dropped copies).
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.chaos.workload")

# generous lineage budget: a soak injects dozens of faults and one object
# may be rebuilt several times — exhausting retries mid-soak would turn a
# liveness check into a false loss signal
TASK_MAX_RETRIES = 50


def expected_payload(i: int, nbytes: int) -> bytes:
    """Deterministic payload: re-executions (lineage rebuilds) re-seal
    byte-identical values under the same object id. Single definition —
    the remote task and the client-side verifier must never drift."""
    return bytes([i % 251]) * nbytes


def _produce(i: int, nbytes: int) -> bytes:
    return expected_payload(i, nbytes)


class ChaosCounter:
    """Restartable actor: state resets on restart by design — chaos
    asserts liveness (ALIVE + responsive within the restart budget), not
    state carry-over."""

    def __init__(self) -> None:
        self.n = 0

    def incr(self) -> int:
        self.n += 1
        return self.n

    def ping(self) -> str:
        return "pong"


class ChaosWorkload:
    def __init__(
        self,
        rt,
        payload_bytes: int = 200_000,
        num_actors: int = 1,
        actor_max_restarts: int = 100,
    ):
        import ray_tpu

        self.rt = rt
        self.payload_bytes = int(payload_bytes)
        self._task = ray_tpu.remote(_produce)
        self._next_i = 0
        # hex -> (ref, task index); acked refs were returned by get() once
        self.acked: Dict[str, Tuple[object, int]] = {}
        self.pending: List[Tuple[object, int]] = []
        self.failed_pending: List[Tuple[str, str]] = []  # (hex, reason)
        self.actors: List[object] = []
        self.actor_ids: List[str] = []
        Actor = ray_tpu.remote(ChaosCounter)
        for k in range(num_actors):
            h = Actor.options(
                name=f"chaos-counter-{k}", max_restarts=actor_max_restarts
            ).remote()
            self.actors.append(h)
            self.actor_ids.append(h._actor_id)
        self.objects_acked = 0
        self.objects_reverified = 0

    # -- traffic -------------------------------------------------------
    def step(self, n_tasks: int = 4) -> None:
        """Submit a batch of producer tasks (results stay pending until
        ``ack``) and poke every actor."""
        for _ in range(n_tasks):
            i = self._next_i
            self._next_i += 1
            ref = self._task.options(
                max_retries=TASK_MAX_RETRIES
            ).remote(i, self.payload_bytes)
            self.pending.append((ref, i))
        for h in self.actors:
            # fire-and-forget liveness traffic; convergence checks do the
            # asserted calls
            h.incr.remote()

    def ack(self, timeout: float = 60.0) -> int:
        """Resolve pending results. Successes become acked; a failure is
        only legal as an exhausted-retry/dead-actor error (recorded, and
        judged by the invariant checker)."""
        import ray_tpu

        still: List[Tuple[object, int]] = []
        n_acked = 0
        deadline = time.monotonic() + timeout
        for ref, i in self.pending:
            budget = max(0.5, deadline - time.monotonic())
            try:
                value = ray_tpu.get(ref, timeout=budget)
            except Exception as exc:  # noqa: BLE001 - judged by invariants
                msg = str(exc)
                if _is_timeout(exc):
                    still.append((ref, i))
                else:
                    self.failed_pending.append((ref.hex, msg))
                continue
            if value != expected_payload(i, self.payload_bytes):
                raise AssertionError(
                    f"task {i} returned corrupted payload "
                    f"({len(value)} bytes)"
                )
            self.acked[ref.hex] = (ref, i)
            self.objects_acked += 1
            n_acked += 1
        self.pending = still
        return n_acked

    # -- invariant probes ---------------------------------------------
    def verify_acked(
        self, sample: int = 8, timeout: float = 60.0
    ) -> List[str]:
        """Re-get the most recent ``sample`` acked objects; returns a list
        of failure descriptions (empty = invariant holds)."""
        import ray_tpu

        failures: List[str] = []
        recent = list(self.acked.values())[-sample:]
        for ref, i in recent:
            try:
                value = ray_tpu.get(ref, timeout=timeout)
            except Exception as exc:  # noqa: BLE001
                failures.append(f"acked object {ref.hex[:8]} lost: {exc!r}")
                continue
            if value != expected_payload(i, self.payload_bytes):
                failures.append(
                    f"acked object {ref.hex[:8]} corrupted "
                    f"({len(value)} bytes)"
                )
            else:
                self.objects_reverified += 1
        return failures

    def verify_ref(self, hex_id: str, timeout: float = 60.0) -> Optional[str]:
        """Re-get ONE acked object by hex; returns a failure description
        or None. The object-drop fault verifies its specific victim with
        this (the sampled sweep may not include it)."""
        import ray_tpu

        entry = self.acked.get(hex_id)
        if entry is None:
            return f"object {hex_id[:8]} is not acked"
        ref, i = entry
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except Exception as exc:  # noqa: BLE001
            return f"dropped object {hex_id[:8]} not rebuilt: {exc!r}"
        if value != expected_payload(i, self.payload_bytes):
            return f"dropped object {hex_id[:8]} rebuilt corrupted"
        self.objects_reverified += 1
        return None

    def sample_acked_ref(self, rng) -> Optional[object]:
        """A random acked ref (the object-drop fault's victim pool)."""
        if not self.acked:
            return None
        key = rng.choice(sorted(self.acked))
        return self.acked[key][0]


def _is_timeout(exc: BaseException) -> bool:
    from ray_tpu.core.object_store import GetTimeoutError

    return isinstance(exc, GetTimeoutError)
