"""Typed configuration registry — every tunable in one place.

Analog of the reference's RayConfig flag system
(/root/reference/src/ray/common/ray_config_def.h:18, ~400 RAY_CONFIG
declarations with env overrides): each knob is declared once with a type,
default, and doc line, and can be overridden by an environment variable
named ``RAY_TPU_<NAME>`` (upper-cased). Reads go through ``cfg.<name>``
and consult the environment live for most knobs; a few structural
constants (inline_object_max, sched_tick_s, sched_max_batch,
dag_buffer_bytes, dag_max_inflight) are bound once at module import, so
set those in the environment before importing ray_tpu (they shape wire
formats and pre-sized buffers).

Dump everything with ``python -m ray_tpu config``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() not in ("0", "false", "no", "off")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: lambda s: int(s, 0),
    float: float,
    str: str,
}


@dataclass(frozen=True)
class ConfigEntry:
    name: str
    type: type
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return f"RAY_TPU_{self.name.upper()}"

    def current(self) -> Any:
        raw = os.environ.get(self.env_var)
        if raw is None:
            return self.default
        if self.type is bool and raw.strip() == "":
            # a SET-but-empty boolean var keeps the default (shell templates
            # leave FLAG= empty to mean "don't change it"); anything else
            # would silently flip opt-in flags like direct_trace on
            return self.default
        try:
            return _PARSERS[self.type](raw)
        except (ValueError, KeyError):
            import logging

            logging.getLogger("ray_tpu.config").warning(
                "ignoring invalid %s=%r (expected %s); using default %r",
                self.env_var,
                raw,
                self.type.__name__,
                self.default,
            )
            return self.default


_REGISTRY: Dict[str, ConfigEntry] = {}


def define(name: str, default: Any, doc: str, type_: Optional[type] = None):
    entry = ConfigEntry(name, type_ or type(default), default, doc)
    _REGISTRY[name] = entry
    return entry


def registry() -> Dict[str, ConfigEntry]:
    return dict(_REGISTRY)


class _Config:
    """Attribute access over the registry; env consulted on every read."""

    def __getattr__(self, name: str) -> Any:
        entry = _REGISTRY.get(name)
        if entry is None:
            raise AttributeError(f"unknown config knob {name!r}")
        return entry.current()

    def dump(self) -> list:
        out = []
        for e in sorted(_REGISTRY.values(), key=lambda x: x.name):
            raw = os.environ.get(e.env_var)
            out.append(
                {
                    "name": e.name,
                    "env": e.env_var,
                    "type": e.type.__name__,
                    "default": e.default,
                    "value": e.current(),
                    "source": "env" if raw is not None else "default",
                    "doc": e.doc,
                }
            )
        return out


cfg = _Config()

# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------
define("sched_tick_s", 0.002, "Head scheduler loop pause between rounds.")
define("sched_max_batch", 4096, "Max leases per scheduling kernel round.")
define(
    "device_scheduler",
    True,
    "Run the live scheduling kernels on an XLA backend (vs NumPy golden).",
)
define(
    "sched_platform",
    "cpu",
    "XLA platform for the live scheduler kernels (cpu keeps dispatch "
    "latency off the accelerator tunnel; tpu offloads the hot loop).",
)
define(
    "sched_init_timeout_s",
    30.0,
    "XLA backend bring-up budget before degrading to the host scheduler.",
)
define("xla_cache", "/tmp/ray_tpu_xla_cache", "JAX compilation cache dir.")
define(
    "sched_device_min_batch",
    0,
    "Batches smaller than this schedule on the host golden model even "
    "when the XLA device scheduler is up (per-dispatch overhead beats "
    "kernel gains for tiny rounds; 0 = always use the device kernels).",
)
define(
    "sched_pipeline",
    True,
    "Pipelined scheduling rounds: round N+1's kernel dispatches while "
    "round N's placements are still being read back (async host copy, "
    "double-buffered through the donated avail chain); grants fan out "
    "from a completion thread. Off: every round blocks on its own "
    "readback inside the scheduler loop (the pre-pipeline behavior).",
)
define(
    "sched_pipeline_depth",
    3,
    "Max scheduling rounds in flight (dispatched, readback pending) "
    "before submit blocks. Bounds host-mirror lag and grant latency; "
    "1 degenerates to the synchronous round with the completion thread "
    "still off the scheduler loop.",
)
define(
    "sched_prewarm",
    True,
    "Background-compile the scheduling kernel for the bucketed "
    "(batch, unique-shape) grid at first device sync (and again after "
    "node-capacity growth), so first-touch rounds stop paying "
    "multi-second jit compile spikes visible as sched_round_ms outliers.",
)
define(
    "sched_ring_slots",
    64,
    "Slots in the on-device parked-demand ring: resource shapes that "
    "failed placement stay resident on the scheduler device (one row "
    "per shape) and retry via a count-driven kernel without re-uploading "
    "demand matrices. 0 disables the ring (parked specs retry through "
    "the normal round path).",
)
define(
    "sched_unpark_device",
    True,
    "Estimate per-shape grantable slots for capacity-capped unparking "
    "on the scheduler device (one batched kernel over the resident "
    "availability arrays) instead of per-shape host NumPy scans.",
)
# --- multi-objective scoring weights (hybrid.ScoreWeights) ---
# (1, 0, 0, 0) recovers the single-objective kernel bit-for-bit; the
# extra terms are skipped at trace time, so the defaults cost nothing.
define(
    "sched_w_util",
    1.0,
    "Weight of the reference-compatible critical-utilization term in the "
    "multi-objective scheduling cost (quantized spread score).",
)
define(
    "sched_w_het",
    0.0,
    "Weight of the heterogeneity term (Gavel-style per-(shape, node-type)"
    " effective-throughput penalty from ClusterView.type_throughput).",
)
define(
    "sched_w_frag",
    0.0,
    "Weight of the fragmentation term (post-placement stranded-capacity "
    "estimate vs the round's largest demand shape): >0 packs small "
    "shapes onto already-broken nodes instead of stranding whole ones.",
)
define(
    "sched_w_starve",
    0.0,
    "Starvation discount of the soft het/frag terms: a shape parked "
    "w_starve-scaled wait-ages stops holding out for a well-scored node "
    "and takes any available one.",
)
define(
    "sched_w_locality",
    0.0,
    "Weight of the data-locality term in the multi-objective scheduling "
    "cost: a per-(shape, node) BONUS for nodes already holding the "
    "task's input-partition bytes (object-directory locations x seal "
    "sizes, uploaded with the demand rows), so shuffle reduce tasks "
    "land where their map partitions live. 0 (default) keeps round "
    "prep and the kernel program byte-identical to the pre-locality "
    "path; specs with different residency split into their own kernel "
    "slots when > 0.",
)
define(
    "sched_starve_rounds",
    32,
    "Park-retry rounds before a demand shape counts as STARVING: its "
    "normalized wait-age crosses 1.0, arming preemption nomination and "
    "maxing the starvation discount.",
)
define(
    "sched_preempt",
    True,
    "Preemption as a first-class scheduler action: a starving shape with "
    "zero capacity anywhere nominates its lowest-cost feasible node in "
    "the round kernel, and the head kills-and-requeues preemptable "
    "victims there (queued leases respill untouched; active worker "
    "leases revoke and spill; running retryable tasks may be killed — "
    "see sched_preempt_running). max_retries=0 victims that already "
    "started are NEVER preempted (at-most-once).",
)
define(
    "sched_preempt_running",
    True,
    "Allow preemption to force-kill a RUNNING task when its lease is "
    "retryable (attempt < max_retries); the kill requeues through the "
    "lineage machinery WITHOUT consuming a retry attempt. Off: only "
    "not-yet-running work and worker leases are preemptable.",
)
define(
    "sched_preempt_max_per_round",
    8,
    "Cap on victim leases preempted per scheduling round (a starvation "
    "storm must drain gradually, not mass-kill the cluster).",
)
define(
    "sched_preempt_cooldown_s",
    2.0,
    "Per-shape cooldown between preemption actions: the freed capacity "
    "needs agent report round-trips to become placeable, so re-preempting"
    " for the same starving shape every round would overshoot.",
)
# --- autoscaler on-device residual solve ---
define(
    "autoscaler_solve",
    True,
    "Solve the autoscaler's residual bin-pack as a fixed-iteration "
    "projected-gradient allocation over DeltaBinPacker's resident "
    "arrays (CvxCluster-style batched iterative solve, arxiv "
    "2605.01614) instead of the O(demands) first-fit scan. The host "
    "greedy remains the oracle and the automatic fallback.",
)
define(
    "autoscaler_solve_iters",
    24,
    "Fixed projected-gradient iteration count of the autoscaler solve "
    "(jit-prewarmed; more iterations sharpen the allocation but the "
    "exact extraction pass keeps any count correct).",
)
define(
    "autoscaler_solve_min_demands",
    64,
    "Demand batches smaller than this pack with the exact first-fit "
    "kernel (per-demand scan beats the solve's fixed overhead there).",
)
define(
    "spill_storage_uri",
    "",
    "External spill storage for the object plane (external_storage.py "
    "analog): empty = node-local spill dir; file:///path; memory://; "
    "s3://bucket/prefix (boto3 or an injected client).",
)
define(
    "streaming_window",
    128,
    "num_returns='streaming' backpressure: max items an executor seals "
    "ahead of the consumer's watermark before pausing (the reference's "
    "_generator_backpressure_num_objects analog).",
)
define(
    "stream_idle_gc_s",
    600.0,
    "Head-side GC: a finished stream untouched this long is dropped and "
    "its undelivered item holds released (abandoned-generator cleanup).",
)
define(
    "trace_tasks",
    True,
    "Mint a root trace context for every untraced task submission "
    "(distributed tracing on by default, reference tracing_helper.py "
    "semantics). Off: only traces opened explicitly via "
    "util.tracing.start_trace() propagate; untraced submissions pay "
    "zero minting cost on the hot path.",
)
define(
    "native_ledger",
    True,
    "Use the C++ fixed-point resource ledger (vs pure-Python fallback).",
)

# ---------------------------------------------------------------------------
# flight recorder (ISSUE 15): federation, spans, attribution, crash bundles
# ---------------------------------------------------------------------------
define(
    "trace_spans",
    True,
    "Record process-level duration spans (scheduler rounds, serve "
    "request lifecycle, socket-plane stripes, elastic reshape phases) "
    "into util.tracing.SPANS; merged into every Chrome-trace export and "
    "crash bundle. All sites are off the per-task hot path.",
)
define(
    "metrics_federation",
    True,
    "Ship typed registry deltas to the head (workers piggyback on the "
    "seal channel, agents on the coalesced head report); the head "
    "merges them into one node/role-labeled scrape body.",
)
define(
    "metrics_interval_s",
    2.0,
    "Registry-delta ship cadence for the metrics federation (workers "
    "and agents collect at most this often; idle registries ship "
    "nothing).",
)
define(
    "sched_explain",
    True,
    "Read back the per-term cost contributions (util/het/frag/locality "
    "+ starvation discount) of every winning placement from the round "
    "kernel and keep them queryable via QueryState explain_placement. "
    "Adds one f32[B,5] readback per round; placements are unchanged.",
)
define(
    "sched_explain_keep",
    4096,
    "Bounded count of per-task placement explanations retained on the "
    "head (oldest evicted first).",
)
define(
    "crash_bundles",
    True,
    "Dump a bounded flight-recorder bundle (recent task events, trace "
    "spans, a metrics snapshot, debug state) on chaos faults, "
    "retries-exhausted task failures, and head failover.",
)
define(
    "crash_bundle_dir",
    "",
    "Base directory for crash bundles (empty = <tmpdir>/ray_tpu_bundles); "
    "each process writes under a per-run subdirectory.",
)
define(
    "crash_bundle_window_s",
    60.0,
    "Crash bundles include only task events / spans from the last this "
    "many seconds.",
)
define(
    "crash_bundle_keep",
    8,
    "Max bundles kept per run directory (oldest rotated out).",
)
define(
    "crash_bundle_min_interval_s",
    5.0,
    "Throttle: at most one crash bundle per process per this interval "
    "(a failure storm must not turn the recorder into the outage).",
)

# ---------------------------------------------------------------------------
# cluster control plane
# ---------------------------------------------------------------------------
define("head_address", "", "Cluster head address for implicit ray_tpu.init().")
define(
    "report_period_s", 0.1, "Agent resource/health report period to the head."
)
define(
    "health_timeout_s",
    8.0,
    "Head marks a node dead after this long without a report. The"
    " reference's detection window is ~15-25s (health_check_period_ms x"
    " failure_threshold); 3s proved twitchy enough to falsely kill nodes"
    " mid-transfer-storm on a loaded 1-core host.",
)
define(
    "health_miss_threshold",
    3,
    "Consecutive missed health windows before the head marks a node dead "
    "(gcs_health_check_manager failure_threshold analog). The window is "
    "health_timeout_s / health_miss_threshold, so total detection latency "
    "stays ~health_timeout_s while a single wall-clock gap (GC pause, "
    "transfer storm on a loaded host) is no longer a death sentence.",
)
define(
    "orphan_timeout_s",
    120.0,
    "An agent that cannot reach any head for this long exits.",
)

# ---------------------------------------------------------------------------
# replicated control plane (warm-standby heads, WAL shipping, failover)
# ---------------------------------------------------------------------------
define(
    "head_shards",
    8,
    "Shard count of the head's owner-sharded directory/lease tables "
    "(object directory, task-lease table, peer-link table). Keys route "
    "by a stable hash, so lookups touch one shard and shipped-WAL "
    "replay applies shard groups conflict-free.",
)
define(
    "head_standbys",
    "",
    "Comma-separated warm-standby head addresses agents/clients walk "
    "(after the primary and any leader hint) when the head stops "
    "answering as leader.",
)
define(
    "head_health_timeout_s",
    2.0,
    "Standby-side leader death detection window: a standby declares the "
    "leader dead after head_miss_threshold consecutive missed probe "
    "windows of head_health_timeout_s / head_miss_threshold each, then "
    "promotes (epoch bump + listener bind).",
)
define(
    "head_miss_threshold",
    3,
    "Consecutive missed leader-probe windows before a warm standby "
    "declares the leader dead and promotes itself (same strike shape as "
    "the head's node health loop).",
)
define(
    "wal_ship_acked",
    False,
    "Acked WAL shipping: the leader's WAL flush waits (bounded by "
    "wal_ship_ack_timeout_s) until every live standby applied the "
    "flushed records. Off (default): shipping is asynchronous — a "
    "leader crash can lose the last in-flight batch, same window as "
    "unreplicated durability today.",
)
define(
    "wal_ship_ack_timeout_s",
    2.0,
    "Bound on one acked-shipping wait; a standby that cannot ack within "
    "it accrues strikes and is dropped from the ack quorum (it re-syncs "
    "when it returns).",
)
define(
    "wal_ship_ring",
    8192,
    "Replication ring capacity (records) on the leader: standbys whose "
    "ack fell further behind than the ring re-sync from a fresh "
    "snapshot instead of replaying records that no longer exist.",
)
define(
    "wal_ship_batch",
    512,
    "Max WAL records per shipped ReplWal batch.",
)
define(
    "revoke_redrive_ttl_s",
    120.0,
    "Pending-revoke WAL rows (lease returns / peer-link revokes queued "
    "but not yet delivered to their agent) older than this whose target "
    "node is gone are dropped by the sweep instead of re-driven forever.",
)

# ---------------------------------------------------------------------------
# rpc retry + circuit breaking (RetryableGrpcClient analog)
# ---------------------------------------------------------------------------
define(
    "rpc_backoff_cap_s",
    2.0,
    "Ceiling on any single RPC retry backoff sleep (decorrelated-jitter "
    "exponential backoff below the cap).",
)
define(
    "rpc_breaker_window_s",
    5.0,
    "A peer whose calls have failed at transport level for this long "
    "with no intervening success gets its circuit opened: calls fail "
    "fast and the node-unreachable callback fires into the health path "
    "(server_unavailable_timeout_seconds analog).",
)
define(
    "rpc_breaker_cooldown_s",
    1.0,
    "How long an open circuit stays open before one half-open probe "
    "call is allowed through; probe success closes it.",
)
define(
    "rpc_breaker_min_failures",
    3,
    "Minimum transport failures (with no intervening success) before the "
    "breaker may open — the window span alone must not let two isolated "
    "large-transfer timeouts read as a dead peer.",
)
define(
    "chaos_seed",
    0,
    "Seed for the deterministic chaos orchestrator (ray_tpu.chaos). The "
    "same seed replays the exact same fault schedule; soak failures "
    "print the seed so they reproduce exactly.",
)

define(
    "rpc_chaos",
    "",
    "Message-level failure injection, e.g. "
    "'ExecuteLeaseBatch:drop=0.1;PushTaskBatch:delay_ms=20' "
    "(rpc_chaos.h analog; parsed once per process).",
)

# ---------------------------------------------------------------------------
# object plane
# ---------------------------------------------------------------------------
define(
    "inline_object_max",
    100 * 1024,
    "Values at or below this many serialized bytes travel inline in "
    "control messages instead of the shared-memory store.",
)
define("native_store", True, "Use the C++ shared-memory object store.")
define(
    "store_bytes",
    1 << 28,
    "Default shared-memory arena capacity per node (bytes).",
)
define("refcount_debug", False, "Record per-ref count history (diagnostics).")
define(
    "runtime_env_idle_gc_s",
    300.0,
    "Reap pip runtime-env workers idle longer than this and GC "
    "unreferenced env directories.",
)
define(
    "max_concurrent_pushes",
    4,
    "Outbound object-transfer slots per agent (push_manager.h in-flight "
    "cap analog); requests are admitted GET > WAIT > TASK_ARGS.",
)
define(
    "max_concurrent_pulls",
    4,
    "Bound on concurrent inbound peer object transfers per node "
    "(pull_manager admission; same-object pulls coalesce regardless).",
)
define(
    "transfer_chunk_bytes",
    4 << 20,
    "Peer object transfers larger than this pull in chunks of this size "
    "(object_manager chunked-push analog) instead of one monolithic "
    "FetchObject reply; a dropped chunk retries alone.",
)
define(
    "transfer_max_inflight_chunks",
    4,
    "Concurrent in-flight chunks per chunked peer pull (push_manager "
    "in-flight cap analog, per transfer).",
)
define(
    "native_net",
    True,
    "Cross-node zero-copy transport: direct worker<->worker data sockets "
    "(native/net.cc sendmsg/recvmsg scatter-gather over RTP5 frames, "
    "head-granted peer connection leases, striping for large objects). "
    "Off: every cross-node transfer rides the chunked-RPC fallback "
    "(object_plane.fetch_chunked). Read live — flip mid-process for "
    "A/B; in-flight transfers finish on their current path.",
)
define(
    "net_stripe_bytes",
    64 << 20,
    "Stripe size for socket peer transfers: objects larger than one "
    "stripe split across parallel connections with per-stripe offsets; "
    "a severed connection re-fetches only its lost stripes (resume).",
)
define(
    "net_stripe_conns",
    4,
    "Max parallel data connections one striped transfer fans out over "
    "(>1 GB objects ride N sockets; single-stripe objects use one).",
)
define(
    "net_inflight_cap_bytes",
    256 << 20,
    "Cap on in-flight (requested, not yet landed) bytes per striped "
    "transfer — backpressure into the receiving arena.",
)
define(
    "net_fetch_inflight_cap_bytes",
    512 << 20,
    "Cap on TOTAL in-flight socket-fetch bytes across all concurrent "
    "peer pulls in one process (a shuffle reduce resolving many "
    "partitions at once must not stage more than this into the arena "
    "before the spill path can drain it). New fetches park until "
    "running ones land; a single transfer larger than the cap still "
    "proceeds alone. 0 disables the gate.",
)
define(
    "device_plane",
    True,
    "Device-direct data plane: jax.Array leaves seal as device frames "
    "(dlpack/__array__ export riding RTP5 out-of-band buffers — on the "
    "CPU backend the export aliases the device buffer, zero-copy) and "
    "land via device_put straight from the arriving arena view / socket "
    "landing zone, skipping the host-bounce copy on both sides. Off: "
    "jax leaves ride cloudpickle's stock reducer (full host copy in the "
    "pickle pass) and land host-side — the pre-device-plane behaviour. "
    "Read live; sealed device frames remain loadable either way.",
)
define(
    "device_pump_min_bytes",
    8 << 20,
    "Device arrays at or above this size on a non-host-aliasing backend "
    "read out through the chunked copy_to_host_async D2H pump "
    "(overlapping readout with the arena gather / socket send) instead "
    "of one monolithic export.",
)
define(
    "device_pump_chunk_bytes",
    4 << 20,
    "Chunk size of the D2H pump (device_pump_min_bytes); each chunk is "
    "one copy_to_host_async window.",
)
define(
    "device_pump_depth",
    4,
    "Max in-flight async D2H chunks the pump keeps ahead of its "
    "consumer.",
)
define(
    "device_land_chunk_bytes",
    4 << 20,
    "Device landing zone H2D chunk size: during a striped socket fetch "
    "with land=device, each completed chunk of the contiguous prefix is "
    "device_put in flight, overlapping H2D with the remaining recv.",
)
define(
    "device_land_always",
    False,
    "Force the device landing zone even on host-aliasing backends (CPU) "
    "where the overlap hides nothing — test / A-B hook; production "
    "leaves this off and the zone activates only when a real H2D hop "
    "exists.",
)
define(
    "peer_link_ttl_s",
    10.0,
    "Renewal horizon of a granted peer data link: agents piggyback "
    "renewals for recently-used links on their seal reports, and the "
    "head's sweep revokes links not renewed within 3x this (dead-holder "
    "safety net; an actively-renewed link never expires).",
)
define(
    "peer_link_idle_ttl_s",
    60.0,
    "Requester-side idle TTL: a cached peer link with no transfer for "
    "this long closes its pooled connections and returns the lease to "
    "the head.",
)
define(
    "worker_shm_reads",
    True,
    "Workers resolve same-node objects as zero-copy read-only views over "
    "the shared-memory arena. Off: every read round-trips the agent as "
    "pickled bytes (debug / perf-comparison fallback).",
)
define(
    "memory_monitor_interval_s",
    1.0,
    "Agent memory-pressure check period; 0 disables OOM killing.",
)
define(
    "memory_usage_threshold",
    0.95,
    "Host memory usage fraction above which the agent kills the newest "
    "plain task's worker to relieve pressure.",
)

# ---------------------------------------------------------------------------
# worker lifecycle (fork-server + warm pool)
# ---------------------------------------------------------------------------
define(
    "fork_server",
    True,
    "Fork new workers from a per-agent zygote process that imported "
    "ray_tpu (and jax, when JAX_PLATFORMS is set) once, instead of a "
    "cold interpreter spawn per worker (reference worker_pool.cc "
    "prestart + Python fork-server semantics). Falls back to cold "
    "spawn automatically when fork is unavailable, the zygote dies, or "
    "a pip/conda runtime env demands its own interpreter.",
)
define(
    "zygote_ready_timeout_s",
    30.0,
    "How long a fork request waits for the zygote's one-time import "
    "warmup before falling back to cold spawn for good.",
)
define(
    "prestart_max_workers",
    16,
    "Cap on extra workers an agent prestarts above num_workers in "
    "response to head PrestartWorkers hints (worker_pool.cc "
    "PrestartWorkers analog).",
)
define(
    "actor_worker_reuse",
    True,
    "Return a worker whose actor exited cleanly to the idle pool after "
    "a scrub (module/env/cwd reset) instead of killing it. Reuse is "
    "denied across pip/conda or persisted runtime envs, and when the "
    "scrub cannot restore pristine state (heavyweight modules imported "
    "by actor code) — those workers are killed and re-forked.",
)

# ---------------------------------------------------------------------------
# direct actor calls
# ---------------------------------------------------------------------------
define(
    "direct_actor_calls",
    True,
    "Submit actor methods caller->worker directly, head off the hot path.",
)
define(
    "direct_inline_wait_s",
    0.005,
    "Worker lingers this long so fast results ride the accept reply.",
)
define(
    "direct_wait_fallback_s",
    10.0,
    "Getter stops trusting the direct result push after this long and "
    "resolves through the head directory.",
)
define(
    "direct_results_cap",
    16384,
    "Driver-side FIFO bound on cached direct-call / leased-task "
    "results. Evicting an owner-held (deferred-seal) entry whose ref is "
    "still live costs a PutObject upload to the head, so the cap should "
    "sit above a driver's typical in-flight ref count — a 10k-task "
    "submit-then-get wave over a 4096 cap paid ~6k serial uploads.",
)
define("direct_trace", False, "Stamp direct-call results with timing marks.")
define(
    "direct_deferred_seals",
    True,
    "Owner-based object bookkeeping for direct actor calls (the "
    "reference's ownership model): a small result delivered to its "
    "caller does NOT seal to the head — the caller holds value + seal "
    "and uploads to the head only when the ref is shared into another "
    "submission or evicted from the local cache. Cuts the per-call "
    "worker->agent->head seal chain off the hot path; a failed result "
    "push falls back to worker-side sealing.",
)

# ---------------------------------------------------------------------------
# task leases (owner-cached direct task dispatch)
# ---------------------------------------------------------------------------
define(
    "task_leases",
    True,
    "Lease-cached direct task dispatch: the head grants owners cacheable "
    "worker leases per task shape (fn hash x resources), and same-shape "
    "tasks stream caller->worker with no head hop (the reference's "
    "local_lease_manager worker leases). Off: every task rides the "
    "per-task head-scheduled path.",
)
define(
    "task_lease_ttl_s",
    5.0,
    "Idle TTL of a cached worker lease: the owner returns a lease this "
    "long after its queue drained; the head's expiry sweep revokes "
    "leases not renewed within 3x this (dead-owner safety net).",
)
define(
    "task_lease_max_inflight",
    64,
    "Tasks in flight (sent, result pending) per cached worker lease. "
    "This is PIPELINE depth, not parallelism — the leased worker "
    "executes one task at a time against the lease's single resource "
    "allocation; parallelism comes from holding more leases.",
)
define(
    "task_lease_max_per_shape",
    8,
    "Max concurrent worker leases one owner holds per task shape; the "
    "cache grows toward this while its queues stay deep.",
)
define(
    "task_lease_stall_s",
    1.0,
    "A lease with results owed but none arriving for this long recalls "
    "its queued (not-yet-running) tasks from the worker and spills them "
    "back to head scheduling — a head-of-line task blocked on other "
    "tasks' results (rendezvous peers) delays followers by ~this "
    "instead of deadlocking the lease.",
)

# ---------------------------------------------------------------------------
# owner liveness + lineage reconstruction + epoch fencing (robustness)
# ---------------------------------------------------------------------------
define(
    "owner_liveness",
    True,
    "Owner fate-sharing: clients heartbeat a session lease to the head "
    "(riding the pipelined ClientBatch); an owner that misses "
    "owner_miss_threshold consecutive windows of owner_lease_ttl_s is "
    "declared dead and fully reaped — non-detached actors killed, cached "
    "worker leases revoked immediately, queued/in-flight tasks cancelled, "
    "and unproduced objects failed with OwnerDiedError. Off: crashed "
    "owners leak actors until explicit kill and leases until 3x TTL.",
)
define(
    "owner_lease_ttl_s",
    10.0,
    "Owner session heartbeat window; clients beat at half this period. "
    "Death is declared after owner_miss_threshold consecutive missed "
    "windows (total detection ~ttl x threshold).",
)
define(
    "owner_miss_threshold",
    3,
    "Consecutive missed owner heartbeat windows before the head declares "
    "the owner dead and reaps its actors/leases/objects.",
)
define(
    "owner_lineage_cap_mb",
    64,
    "Byte budget (MiB) for the owner-side lineage cache: leased direct-"
    "dispatch tasks never register a spec with the head, so the OWNER "
    "retains each task's payload keyed by its return ref and resubmits "
    "through head scheduling when the head reports the object lost "
    "without re-executable lineage (the reference's ownership model — "
    "lineage lives with the owner). Oldest entries evict past the cap; "
    "an evicted object's loss is then permanent (ObjectLostError).",
)
define(
    "reconstruction_max_depth",
    8,
    "Bound on the recursive lineage reconstruction walk: an object whose "
    "rebuild requires re-executing more than this many generations of "
    "lost inputs fails with a reconstruction-depth error instead of "
    "walking an unbounded chain.",
)
define(
    "epoch_fencing",
    True,
    "Epoch-fenced control plane: head restarts bump a persisted cluster "
    "epoch; agents and owners stamp their control RPCs with the epoch "
    "they joined under, and stale-epoch traffic is rejected with a "
    "non-retryable RpcStaleEpochError (the sender re-registers to adopt "
    "the new epoch). Off: a partitioned pre-restart agent's reports can "
    "land on a rebuilt head unfenced.",
)

# ---------------------------------------------------------------------------
# serving plane (ray_tpu.serve router/admission/prefix-cache/autoscaler)
# ---------------------------------------------------------------------------
define(
    "serve_push_streams",
    True,
    "Stream token deltas from replicas straight to the ingress process's "
    "push sink (direct worker->ingress RPC, zero head involvement, no "
    "polling). Off: cross-host streams fall back to the legacy polling "
    "_StreamRelayActor bridge.",
)
define(
    "serve_shm_streams",
    True,
    "Prefer the same-host shm ring Channel for token streams when a "
    "same-host replica exists (zero-RPC transport). Off: every stream "
    "rides the push sink — mainly a test lever to force the push path.",
)
define(
    "serve_stream_buffer",
    4096,
    "Per-stream bound on buffered undelivered deltas at the ingress "
    "push sink; writers past it are rejected (backpressure is "
    "depth-based and writer-side, like the relay actor's contract).",
)
define(
    "serve_stream_failover",
    1,
    "Max mid-stream replica failovers per request: on replica death a "
    "resumable deployment is re-dispatched elsewhere with "
    "resume_from=<delivered count> so acked deltas are neither repeated "
    "nor lost. 0 disables failover (streams error on replica death).",
)
define(
    "serve_admission_qps",
    0.0,
    "Token-bucket sustained admission rate for the serving router "
    "(requests/s); 0 = unlimited (depth shedding still applies).",
)
define(
    "serve_admission_burst",
    32.0,
    "Token-bucket burst allowance above the sustained admission rate.",
)
define(
    "serve_admission_max_inflight",
    256,
    "Admitted-but-unfinished request bound at the router; arrivals past "
    "it queue in the WFQ waiting room or shed with Overloaded.",
)
define(
    "serve_admission_wait_cap",
    128,
    "Bound on the admission waiting room (all tenants); past it "
    "arrivals shed immediately with reason=queue_full.",
)
define(
    "serve_admission_timeout_s",
    2.0,
    "Max time one arrival waits in the WFQ room before shedding with "
    "reason=timeout.",
)
define(
    "serve_prefix_cache",
    True,
    "Cross-replica prefix/KV cache in the node's shm arena: page-aligned "
    "prompt prefixes hit as read-only view pins and skip prefill "
    "compute. Off: every prompt prefills from scratch.",
)
define(
    "serve_prefix_cache_bytes",
    64 << 20,
    "Per-inserting-process byte budget for prefix KV entries in the "
    "arena (oldest own entries evict first; arena-full puts evict then "
    "retry once).",
)
define(
    "serve_report_period_s",
    1.0,
    "Router -> head serve-state report period (powers QueryState('serve')"
    "); control-plane cadence, never per-request.",
)
define(
    "serve_autoscale_interval_s",
    0.5,
    "SLO autoscaler control-loop tick.",
)
define(
    "serve_routers",
    1,
    "Ingress router replicas per deployment (the router fleet). Tenants "
    "map to routers by consistent hash; each router runs its own "
    "admission shard and push sink. 1 = the single-router layout.",
)
define(
    "serve_ring_vnodes",
    64,
    "Virtual nodes per router on the tenant->router consistent-hash "
    "ring (higher = smoother ranges, slower ring rebuild).",
)
define(
    "serve_budget_reconcile_s",
    0.25,
    "Router-fleet budget reconcile period: each router reports per-"
    "tenant usage/demand and receives its share of the global admission "
    "rate (and flushes stream delivered-count checkpoints).",
)
define(
    "serve_stream_ckpt_every",
    8,
    "Delivered-count checkpoint granularity for fleet streams: a "
    "stream's row is re-checkpointed to the head once it advanced this "
    "many deltas since the last flush (finished streams always flush).",
)
define(
    "serve_drain_timeout_s",
    30.0,
    "Graceful-drain budget for a retiring replica: in-flight streams "
    "finish within this before the replica is killed anyway.",
)
define(
    "serve_slo_ttft_ms",
    0.0,
    "Target p50 time-to-first-token for SLO autoscaling (ms); sustained "
    "violation scales replicas up. 0 disables the TTFT term (queue-"
    "depth scaling still applies).",
)
define(
    "serve_slo_queue_per_replica",
    4.0,
    "Target admitted-in-flight requests per replica: sustained excess "
    "scales up, sustained idleness (below half) drains one replica.",
)
define(
    "serve_swap_drain_deadline_s",
    30.0,
    "Deadline for swap_params' drain of in-flight sequences: past it, "
    "still-active slots are force-evicted (their output truncated at "
    "the tokens generated so far) and parked submits are rejected with "
    "Overloaded(reason='weights_swap') instead of hanging. 0 restores "
    "the legacy unbounded drain.",
)

# ---------------------------------------------------------------------------
# online-RL loop
# ---------------------------------------------------------------------------
define(
    "rl_staleness_window",
    2,
    "Off-policy staleness window K for the online-RL loop: trajectories "
    "stamped with a weights epoch older than committed-K are dropped "
    "and counted (dropped_stale), never silently trained on.",
)
define(
    "rl_publish_interval_steps",
    4,
    "Trainer steps between weight publishes in the online-RL loop: "
    "every interval the trainer seals params into the object plane and "
    "runs the two-phase (seal->commit) weights-epoch publish.",
)

# ---------------------------------------------------------------------------
# compiled DAG
# ---------------------------------------------------------------------------
define(
    "dag_buffer_bytes",
    1 << 22,
    "Default per-edge shm ring capacity for compiled DAGs.",
)
define(
    "dag_max_inflight",
    16,
    "Default max concurrently admitted executions per compiled DAG.",
)

# ---------------------------------------------------------------------------
# execution-plane hot path (fused event loop + AOT actor pipelines)
# ---------------------------------------------------------------------------
define(
    "hotpath_senders",
    8,
    "Sender-pool size for the owner-side fused submit/result event loop "
    "(blocking lease-window / direct-push RPCs run here; the loop thread "
    "itself never blocks on the wire).",
)
define(
    "native_wire",
    True,
    "Use the C framing hot path (native/wire.cc) for the RTP5 pickle-5 "
    "wire format. Read ONCE at serialization import; set "
    "RAY_TPU_NATIVE_WIRE=0 before the first ray_tpu import to force the "
    "pure-Python framing fallback.",
)
define(
    "pipeline_buffer_bytes",
    1 << 22,
    "Per-stage shm ring capacity for AOT-compiled actor pipelines "
    "(compile_pipeline).",
)
define(
    "pipeline_max_inflight",
    64,
    "Max concurrently admitted executions per compiled actor pipeline "
    "(the slot-multiplexed window; backpressure beyond it).",
)
define(
    "pipeline_stall_s",
    5.0,
    "Per-owed-item quiet budget (capped at 10x) before a compiled "
    "pipeline presumes a stage worker dead and spills every unresolved "
    "execution back to the eager task path.",
)

# ---------------------------------------------------------------------------
# data (streaming executor)
# ---------------------------------------------------------------------------
define(
    "data_inflight_budget_bytes",
    256 << 20,
    "Per-stage in-flight byte budget for the Data streaming executor "
    "(resource_manager.py analog); block bytes are estimated from the "
    "first materialized block of each stage.",
)
define(
    "data_actor_idle_reap_s",
    10.0,
    "Actor-pool map workers idle longer than this (above min_size) are "
    "reaped by the streaming executor.",
)
define(
    "data_max_tasks_in_flight_per_actor",
    2,
    "Default per-actor in-flight cap for actor-pool map operators "
    "(pipelines the next block behind the running one).",
)
define(
    "data_vector_shuffle",
    True,
    "Vectorized shuffle partitioning for numeric blocks (hash/bincount "
    "+ stable-argsort gather instead of per-row list appends; ndarray "
    "blocks keep their partitions as buffer-backed arrays so the "
    "pickle-5 frames scatter-write straight into the shm arena). Off: "
    "the generic row loop, kept as the fallback for non-numeric keys "
    "and as the bench baseline.",
)
define(
    "data_shuffle_eager_free",
    True,
    "Free each shuffle partition's map refs as its reduce task seals "
    "(_flush_frees-style batches) instead of retaining every "
    "map-partition ref until the whole reduce stage completes — bounds "
    "arena fill by in-flight reduces, not dataset size. Freed "
    "partitions are no longer available to re-reconstruct an "
    "ALREADY-SEALED reduce output (same trade as the streaming "
    "executor's eager intermediate frees).",
)
define(
    "data_prefetch_batches",
    2,
    "Default prefetch depth (in blocks) of streaming dataset ingest: "
    "iter_batches pulls this many upcoming blocks over the object "
    "plane concurrently with the consumer's step, so a training loop "
    "overlaps shuffle tail latency instead of stalling per block. Used "
    "by train dataset shards; Dataset.iter_batches defaults to 0 "
    "(off) unless prefetch_batches is passed.",
)
define(
    "elastic_seal_interval_steps",
    10,
    "Elastic training: every N completed steps each rank seals its "
    "param/optimizer state shard into the shm object plane (arena-"
    "direct pickle-5 frames) as the checkpoint-free recovery point for "
    "ranks that later die with their node. 0 disables periodic seals "
    "(break-time seals still happen).",
)
define(
    "elastic_buddy_replicate",
    True,
    "Elastic training: after a periodic state seal, the rank's buddy "
    "(next rank, usually another node) pulls the sealed object through "
    "its agent so the object directory holds a second arena copy — a "
    "single node death can never lose a state shard. Rides the PR 11 "
    "socket plane like any located pull.",
)
define(
    "elastic_grow_poll_s",
    1.0,
    "Elastic training: driver-side capacity poll period. When the gang "
    "runs below its target world size and the cluster again advertises "
    "enough free capacity, the driver fences the gang and grows the "
    "mesh back.",
)
define(
    "elastic_hub_timeout_s",
    60.0,
    "Elastic training: per-collective rendezvous timeout at the gang "
    "hub. A rank parked past this raises and treats the op as revoked "
    "(the gang-epoch protocol decides whether it really was).",
)
define(
    "elastic_place_wait_s",
    15.0,
    "Elastic training: per-attempt placement-group wait when placing a "
    "gang generation. Short on purpose — an over-optimistic world size "
    "(e.g. the head has not yet declared a corpse dead) must fail fast "
    "into the shrink-to-what-fits retry path instead of parking the "
    "whole gang.",
)
define(
    "gang_sync_max_wait_s",
    20.0,
    "Head-side cap on one GangSync long-poll window; drivers re-arm "
    "the poll, so detection latency is governed by the health loop, "
    "not this cap.",
)
define(
    "elastic_controller",
    False,
    "Unified elasticity plane (PR 19): one head-resident controller "
    "tick folds serve pressure, gang grow-back wants, and parked task "
    "demand into a single weighted demand matrix and runs one batched "
    "device solve driving provision/retire, serve capacity hints, and "
    "drain-ahead migration. OFF by default: the three legacy loops "
    "(autoscaler tick, serve SLO tick, elastic grow probe) run "
    "bit-for-bit unchanged.",
)
define(
    "elastic_tick_s",
    1.0,
    "Elasticity controller tick period: one snapshot + one device "
    "solve + actuation per tick.",
)
define(
    "elastic_w_serve",
    3.0,
    "Priority weight of SERVE demand rows (per-tenant replica "
    "pressure) in the unified elasticity solve. Higher-weighted "
    "classes take the waterfall extraction first, so they hold first "
    "claim on every node's capacity.",
)
define(
    "elastic_w_gang",
    2.0,
    "Priority weight of GANG demand rows (grow-back deficits) in the "
    "unified elasticity solve.",
)
define(
    "elastic_w_task",
    1.0,
    "Priority weight of TASK demand rows (parked/deferred queue "
    "shapes) in the unified elasticity solve.",
)
define(
    "elastic_provision_max",
    4,
    "Max nodes the elasticity controller will provision per tick; "
    "also the number of simulated-provisionable node rows appended to "
    "the solve, so the solver can only justify what the provider is "
    "allowed to create.",
)
define(
    "elastic_node_cpus",
    2.0,
    "CPU resources of one hypothetical provisionable node when no "
    "provider node_template is attached.",
    float,
)
define(
    "elastic_min_nodes",
    1,
    "Retirement floor: the elasticity controller never drains the "
    "fleet below this many alive nodes.",
)
define(
    "elastic_idle_retire_s",
    30.0,
    "A node must be solver-idle (zero demand placed on it) AND "
    "lease-idle for this long before it becomes a retirement "
    "candidate.",
)
define(
    "elastic_retire_max",
    1,
    "Max nodes entering drain per controller tick — retirement is "
    "deliberately slower than provisioning so a demand blip cannot "
    "flap the fleet.",
)
define(
    "elastic_drain_deadline_s",
    20.0,
    "Drain-ahead deadline: a retiring node gets this long for its "
    "migrated work to land elsewhere before the provider terminates "
    "it regardless.",
)
