"""Checkpoint: directory snapshot persisted to storage_path.

Parity with ray.train.Checkpoint (/root/reference/python/ray/train/
_checkpoint.py): a checkpoint IS a directory; helpers move pytrees in and
out of it. Model state uses orbax-compatible layout when available, with a
portable numpy .npz fallback (works identically for restore).
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
from contextlib import contextmanager
from typing import Any, Dict, Optional

import numpy as np


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextmanager
    def as_directory(self):
        yield self.path

    # -- pytree helpers (TPU-first: params are jax/numpy pytrees) -------
    @classmethod
    def from_state(cls, state: Dict[str, Any], path: str) -> "Checkpoint":
        """Persist a {name: pytree-or-json-able} dict as a checkpoint dir.

        ATOMIC: everything lands in a sibling temp dir first and
        ``os.replace``s into place, with ``checkpoint_meta.json``
        written last as the commit marker — a crash mid-write leaves
        either the old complete checkpoint or a ``.tmp-*`` orphan,
        never a half-written directory a restore could pick up."""
        import jax

        path = os.path.abspath(path)
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        tmp = tempfile.mkdtemp(
            prefix=os.path.basename(path) + ".tmp-", dir=parent
        )
        try:
            meta: Dict[str, str] = {}
            for name, value in state.items():
                if _is_pytree_of_arrays(value):
                    leaves, treedef = jax.tree.flatten(value)
                    np.savez(
                        os.path.join(tmp, f"{name}.npz"),
                        **{
                            str(i): np.asarray(x)
                            for i, x in enumerate(leaves)
                        },
                    )
                    with open(
                        os.path.join(tmp, f"{name}.treedef.pkl"), "wb"
                    ) as f:
                        pickle.dump(treedef, f)
                    meta[name] = "pytree"
                else:
                    with open(os.path.join(tmp, f"{name}.pkl"), "wb") as f:
                        pickle.dump(value, f)
                    meta[name] = "pickle"
            with open(os.path.join(tmp, "checkpoint_meta.json"), "w") as f:
                json.dump(meta, f)
            try:
                os.replace(tmp, path)
            except OSError:
                # target exists non-empty (caller overwrites a previous
                # checkpoint at the same path): drop it, then swap
                shutil.rmtree(path, ignore_errors=True)
                os.replace(tmp, path)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return cls(path)

    def load_state(self) -> Dict[str, Any]:
        import jax

        with open(os.path.join(self.path, "checkpoint_meta.json")) as f:
            meta = json.load(f)
        out: Dict[str, Any] = {}
        for name, kind in meta.items():
            if kind == "pytree":
                data = np.load(os.path.join(self.path, f"{name}.npz"))
                leaves = [data[str(i)] for i in range(len(data.files))]
                with open(
                    os.path.join(self.path, f"{name}.treedef.pkl"), "rb"
                ) as f:
                    treedef = pickle.load(f)
                out[name] = jax.tree.unflatten(treedef, leaves)
            else:
                with open(os.path.join(self.path, f"{name}.pkl"), "rb") as f:
                    out[name] = pickle.load(f)
        return out


def _is_pytree_of_arrays(value: Any) -> bool:
    import jax

    leaves = jax.tree.leaves(value)
    return bool(leaves) and all(
        isinstance(x, (np.ndarray, np.generic)) or hasattr(x, "__array__")
        for x in leaves
    )
