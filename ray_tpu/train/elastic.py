"""Elastic SPMD training: checkpoint-free recovery, mesh reshape, and
resume from object-plane state lineage.

The legacy ``JaxTrainer`` answers every failure with a whole-gang
restart from the latest *disk* checkpoint. This module makes the
training plane elastic instead:

- **Gang-epoch membership.** The head owns a gang table (rank ->
  node); its health/strike machinery bumps the gang epoch the moment a
  member's node is declared dead (``GangRegister``/``GangSync``/
  ``GangFence``). Every collective the ranks run is fenced by that
  epoch at the gang's rendezvous hub — a straggler from a dead epoch is
  rejected exactly like a stale control RPC at the cluster fence.
- **Object-plane state, not disk.** Each rank periodically seals its
  param/optimizer state into the shm object plane as pickle-5 frames
  (arena-direct via the worker seal path; numpy leaves never re-copy
  through a monolithic pickle), and seals its EXACT boundary state when
  an epoch breaks. A buddy rank pulls each periodic seal over the
  socket plane so the directory holds two arena copies — one node death
  can never lose a shard. Dataset blocks feeding the loop are task
  outputs and reconstruct through the normal lineage machinery.
- **Mesh reshape.** On a membership change the driver re-plans the
  dp/pp/tp topology over the surviving capacity (placement rides the
  ordinary PG/kernel path, with soft ``avoid_nodes`` anti-affinity for
  recently-dead hosts), spawns the new generation, and each rank
  regathers its state shards from the sealed objects — then *grows* the
  mesh back when the autoscaler restores capacity.
- **Reshape-invariant arithmetic.** Collectives reduce over a FIXED
  grid of *virtual shards* (``ElasticConfig.virtual_shards``), summed
  in shard order regardless of how many ranks currently own them, so a
  dp shrink/grow preserves the numerics of the unreshaped run — with
  exactly-representable data, bit-for-bit (test-pinned).

Declarative parameter sharding follows the partition-rule/pjit exemplar
shape: ``match_partition_rules`` maps regex rules over named leaf paths
to ``PartitionSpec``s and ``make_shard_and_gather_fns`` turns the spec
tree into per-leaf device shard/gather callables over the rank's local
mesh.
"""
from __future__ import annotations

import logging
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from functools import reduce as _reduce
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.config import cfg
from ray_tpu.util.metrics import Counter as _Counter
from ray_tpu.util.metrics import Histogram as _Histogram
from ray_tpu.util.tracing import SPANS

from .checkpoint import Checkpoint
from .session import TrainContext, _set_context
from .trainer import Result, RunConfig, ScalingConfig

logger = logging.getLogger(__name__)

ELASTIC_RESHAPES = _Counter(
    "elastic_reshapes_total",
    "Elastic gang generation changes, by direction (shrink = fewer "
    "ranks than the previous generation, grow = more, flat = same "
    "world re-placed, e.g. after a hub death).",
    label_names=("direction",),
)
ELASTIC_SEAL_BYTES = _Counter(
    "elastic_state_sealed_bytes_total",
    "Bytes of param/optimizer state sealed into the object plane by "
    "elastic ranks (periodic + break-time seals).",
)
ELASTIC_SEAL_MS = _Histogram(
    "elastic_seal_ms",
    "Wall time of one rank state seal (flatten + arena-direct write).",
)
ELASTIC_DISK_RESTORES = _Counter(
    "elastic_disk_restores_total",
    "Times an elastic restore had to fall back to a DISK checkpoint "
    "because no object-plane seal set covered the state (the chaos "
    "acceptance gate asserts this stays zero).",
)


class GangEpochRevoked(RuntimeError):
    """This rank's gang epoch was fenced: a member died, the owner
    requested a resize, or the rendezvous hub vanished. The rank seals
    its boundary state and returns to the driver for reshape."""


class ElasticStateIncomplete(RuntimeError):
    """No available seal set covers the full state pytree."""


# ---------------------------------------------------------------------------
# declarative parameter sharding (partition-rule / pjit exemplar shape)
# ---------------------------------------------------------------------------


def tree_paths_and_leaves(tree: Any) -> Tuple[List[str], List[Any], Any]:
    """Flatten ``tree`` into ('/'-joined named paths, leaves, treedef)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = []
    for kp, _ in flat:
        parts = []
        for k in kp:
            name = getattr(k, "key", None)
            if name is None:
                name = getattr(k, "idx", None)
            if name is None:
                name = str(k)
            parts.append(str(name))
        paths.append("/".join(parts))
    return paths, [leaf for _, leaf in flat], treedef


def match_partition_rules(rules: Sequence[Tuple[str, Any]], params: Any):
    """Return a pytree of PartitionSpec according to regex ``rules``
    over '/'-joined leaf paths. Scalars never partition; a leaf no rule
    matches raises (a silent replicate hides typos in the rule table)."""
    from jax.sharding import PartitionSpec as P
    import jax

    paths, leaves, treedef = tree_paths_and_leaves(params)
    specs = []
    for path, leaf in zip(paths, leaves):
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            specs.append(P())
            continue
        for rule, ps in rules:
            if re.search(rule, path) is not None:
                specs.append(ps)
                break
        else:
            raise ValueError(f"partition rule not found for param: {path}")
    return jax.tree.unflatten(treedef, specs)


def make_shard_and_gather_fns(partition_specs: Any, mesh: Any):
    """(shard_fns, gather_fns) pytrees from a PartitionSpec pytree over
    ``mesh``: shard places a host leaf onto the mesh with its spec's
    NamedSharding, gather pulls it back to host numpy. PartitionSpec is
    a tuple subclass, so it must be pinned as a LEAF or tree_map would
    recurse into the spec itself (P() would vanish as an empty node)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def make_shard(spec):
        def shard(x):
            return jax.device_put(x, NamedSharding(mesh, spec))

        return shard

    def make_gather(_spec):
        def gather(x):
            return np.asarray(jax.device_get(x))

        return gather

    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    shard_fns = jax.tree.map(make_shard, partition_specs, is_leaf=is_spec)
    gather_fns = jax.tree.map(make_gather, partition_specs, is_leaf=is_spec)
    return shard_fns, gather_fns


def apply_shard_rules(state: Any, rules: Sequence[Tuple[str, Any]], mesh: Any):
    """Place ``state`` onto ``mesh`` per declarative partition rules:
    flatten once, zip leaves with their matched specs (structure-safe
    via flatten_up_to), device_put each with its NamedSharding."""
    import jax
    from jax.sharding import NamedSharding

    _, leaves, treedef = tree_paths_and_leaves(state)
    specs = match_partition_rules(rules, state)
    spec_leaves = treedef.flatten_up_to(specs)
    placed = [
        jax.device_put(x, NamedSharding(mesh, s))
        for x, s in zip(leaves, spec_leaves)
    ]
    return jax.tree.unflatten(treedef, placed)


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


@dataclass
class ElasticConfig:
    """Elastic gang shape + state-plane policy.

    The elastic axis is dp ACROSS ranks; ``pp``/``tp`` describe each
    rank's in-process device mesh (``MeshConfig(dp=world/pp/tp...)``
    degenerates to 1 device under tests). World sizes are multiples of
    ``pp * tp``; the gang shrinks to the largest feasible multiple and
    grows back toward ``max_workers`` when capacity returns."""

    min_workers: int = 1
    max_workers: int = 1
    pp: int = 1
    tp: int = 1
    # fixed virtual-shard grid for reshape-invariant collectives; None
    # -> max_workers
    virtual_shards: Optional[int] = None
    # every N steps each rank seals state into the object plane; None ->
    # cfg.elastic_seal_interval_steps
    seal_interval_steps: Optional[int] = None
    # regexes over '/'-joined state paths sealed dp-SHARDED (ZeRO-style:
    # each rank seals only its virtual slices; regather concatenates)
    elastic_shard_rules: Tuple[str, ...] = ()
    # device-level sharding rules per the partition-rule exemplar,
    # applied to restored state over the rank's local mesh
    shard_rules: Tuple[Tuple[str, Any], ...] = ()
    grow: bool = True
    placement_strategy: str = "SPREAD"
    resources_per_worker: Optional[Dict[str, float]] = None
    # how many past seal generations stay alive in the object plane
    keep_generations: int = 2
    # bounded drain after a fence before stragglers are killed
    fence_drain_s: float = 30.0
    # give up if no generation has been placeable for this long
    place_deadline_s: float = 300.0

    def world_for(self, ranks_available: int) -> int:
        cell = max(1, self.pp * self.tp)
        world = (min(ranks_available, self.max_workers) // cell) * cell
        return max(world, 0)


# ---------------------------------------------------------------------------
# gang rendezvous hub (epoch-fenced collective rendezvous + seal registry)
# ---------------------------------------------------------------------------


class _GangHubActor:
    """Asyncio rendezvous for one gang. Every op is stamped with the
    sender's gang epoch; a mismatch returns a ``revoked`` sentinel
    instead of data (stale stragglers rejected like stale control
    RPCs). ``set_epoch`` wakes every parked waiter so survivors break
    out of a dead generation's collective immediately instead of
    waiting out the rendezvous timeout. Doubles as the gang's seal
    registry: ranks note their periodic seal ids here and the driver
    polls the registry into its restore cache."""

    def __init__(self, gang_id: str, epoch: int, world: int):
        import asyncio

        self.gang_id = gang_id
        self.epoch = int(epoch)
        self.world = int(world)
        self.slots: Dict[str, Dict[int, Any]] = {}
        self.events: Dict[str, Any] = {}
        self.remaining: Dict[str, set] = {}
        # rank -> recent [{"step","hex","vidx"}, ...]. A short history,
        # not just the latest: ranks seal asynchronously, so at a fault
        # boundary the newest entries straddle two waves — the driver
        # needs the previous wave too or no single step has coverage.
        self.seals: Dict[int, List[dict]] = {}
        self.seal_history = 4
        self._asyncio = asyncio

    async def configure(self, epoch: int, world: int) -> int:
        """Driver arms the next generation: bump epoch, reset world and
        rendezvous state, fail every parked waiter of the old epoch."""
        self.epoch = int(epoch)
        self.world = int(world)
        self.slots.clear()
        self.remaining.clear()
        for ev in self.events.values():
            ev.set()
        self.events.clear()
        return self.epoch

    async def set_epoch(self, epoch: int) -> int:
        if int(epoch) > self.epoch:
            self.epoch = int(epoch)
            for ev in self.events.values():
                ev.set()
        return self.epoch

    async def collect(
        self,
        op_id: str,
        epoch: int,
        rank: int,
        value: Any,
        timeout: float = 60.0,
    ):
        if int(epoch) != self.epoch:
            return {"revoked": self.epoch}
        s = self.slots.setdefault(op_id, {})
        s[rank] = value
        ev = self.events.setdefault(op_id, self._asyncio.Event())
        if len(s) == self.world:
            ev.set()
        else:
            try:
                await self._asyncio.wait_for(ev.wait(), timeout)
            except self._asyncio.TimeoutError:
                if not ev.is_set():
                    s.pop(rank, None)
                    if not s:
                        self.slots.pop(op_id, None)
                        self.events.pop(op_id, None)
                        self.remaining.pop(op_id, None)
                    return None
        if int(epoch) != self.epoch:
            # fenced while parked: contributions of the dead epoch are
            # garbage now — never hand out a partial gather
            return {"revoked": self.epoch}
        out = [s[r] for r in range(self.world)]
        rem = self.remaining.setdefault(op_id, set(range(self.world)))
        rem.discard(rank)
        if not rem:
            self.slots.pop(op_id, None)
            self.events.pop(op_id, None)
            self.remaining.pop(op_id, None)
        return out

    async def note_seal(
        self, rank: int, step: int, hex_id: str, vidx: List[int], epoch: int
    ) -> None:
        if int(epoch) == self.epoch:
            entries = self.seals.setdefault(int(rank), [])
            entries.append(
                {
                    "step": int(step),
                    "hex": hex_id,
                    "vidx": list(vidx),
                    "epoch": int(epoch),
                }
            )
            del entries[: -self.seal_history]

    async def seal_registry(self) -> Dict[int, List[dict]]:
        return {r: list(es) for r, es in self.seals.items()}


class GangContext:
    """Per-rank view of the gang: epoch-fenced collectives over the
    fixed virtual-shard grid. Any hub transport failure (dead hub
    actor, dead node) is surfaced as ``GangEpochRevoked`` — the caller
    seals its boundary state and hands control back for reshape."""

    def __init__(
        self,
        hub,
        gang_id: str,
        rank: int,
        world: int,
        epoch: int,
        virtual_shards: int,
        timeout_s: Optional[float] = None,
    ):
        self.hub = hub
        self.gang_id = gang_id
        self.rank = int(rank)
        self.world = int(world)
        self.epoch = int(epoch)
        self.virtual_shards = int(virtual_shards)
        self.timeout_s = float(
            cfg.elastic_hub_timeout_s if timeout_s is None else timeout_s
        )
        self._counters: Dict[str, int] = {}

    # -- virtual shards -------------------------------------------------
    def owned_shards(self, step: Optional[int] = None) -> List[int]:
        """Virtual shards this rank owns. Ownership is a pure function
        of (shard, world) so it is stable within a generation and
        repartitions automatically on reshape."""
        return [
            v for v in range(self.virtual_shards) if v % self.world == self.rank
        ]

    # -- fenced rendezvous ---------------------------------------------
    def _op_id(self, op: str) -> str:
        n = self._counters.get(op, 0)
        self._counters[op] = n + 1
        return f"{op}:{n}"

    def _rendezvous(self, op: str, value: Any) -> List[Any]:
        op_id = self._op_id(op)
        try:
            out = ray_tpu.get(
                self.hub.collect.remote(
                    op_id, self.epoch, self.rank, value, self.timeout_s
                ),
                timeout=self.timeout_s + 30.0,
            )
        except GangEpochRevoked:
            raise
        except Exception as exc:  # noqa: BLE001 - hub/node death
            raise GangEpochRevoked(
                f"gang {self.gang_id} op {op_id}: hub unreachable ({exc!r})"
            ) from exc
        if out is None:
            raise GangEpochRevoked(
                f"gang {self.gang_id} op {op_id}: rendezvous timed out "
                f"({self.world} ranks expected)"
            )
        if isinstance(out, dict) and "revoked" in out:
            raise GangEpochRevoked(
                f"gang {self.gang_id} op {op_id}: epoch {self.epoch} fenced "
                f"(hub at {out['revoked']})"
            )
        return out

    def allgather(self, value: Any) -> List[Any]:
        return self._rendezvous("allgather", value)

    def barrier(self) -> None:
        self._rendezvous("barrier", None)

    def allreduce_shards(self, partials: Dict[int, Any]) -> Any:
        """Reduce per-virtual-shard pytree partials across the gang.

        Every rank contributes ``{virtual_shard: pytree}`` for the
        shards it owns; every rank receives the tree-sum over ALL
        shards, accumulated in ascending shard order — the summation
        tree is a function of the virtual grid, not of the current
        world size, which is what makes a dp shrink/grow numerically
        invisible."""
        import jax

        gathered = self._rendezvous("allreduce_shards", partials)
        merged: Dict[int, Any] = {}
        for d in gathered:
            merged.update(d)
        if len(merged) != self.virtual_shards:
            raise GangEpochRevoked(
                f"gang {self.gang_id}: shard coverage "
                f"{sorted(merged)} != {self.virtual_shards} virtual shards"
            )
        ordered = [merged[v] for v in sorted(merged)]
        return jax.tree.map(
            lambda *xs: _reduce(np.add, xs), *ordered
        )


# ---------------------------------------------------------------------------
# state sealing / regather (the object-plane checkpoint-free recovery plane)
# ---------------------------------------------------------------------------

# local-mode fallback: put() refs must outlive the sealing call
_LOCAL_SEAL_REFS: Dict[str, Any] = {}


def _matches_any(path: str, rules: Sequence[str]) -> bool:
    return any(re.search(r, path) is not None for r in rules)


def _host_leaves(leaves: List[Any]) -> List[Any]:
    import jax

    out = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            leaf = np.asarray(jax.device_get(leaf))
        out.append(leaf)
    return out


def _seal_leaves_device() -> bool:
    """Whether seal_rank_state may keep jax leaves device-resident: the
    device plane then seals each as a device frame (zero-copy export on
    host-aliasing backends, chunked D2H pump elsewhere) instead of the
    _host_leaves device_get bounce."""
    from ray_tpu.cluster import device_plane

    return device_plane.device_plane_enabled()


def _split_sizes(n: int, parts: int) -> List[int]:
    """np.array_split's split sizes, computed without materializing the
    array host-side — the device path MUST cut the exact same
    boundaries as the host path or regather would frankenstein shards
    from mixed-format seal waves."""
    q, r = divmod(n, parts)
    return [q + 1] * r + [q] * (parts - r)


def seal_rank_state(
    state: Any,
    step: int,
    rank: int,
    world: int,
    virtual_shards: int,
    elastic_shard_rules: Sequence[str] = (),
    owner: str = "",
) -> Tuple[str, List[int]]:
    """Seal this rank's slice of ``state`` at ``step`` into the object
    plane. Returns (hex id, owned virtual-shard indices).

    Leaves whose path matches an elastic shard rule are sealed
    dp-sharded: split into the fixed virtual grid along axis 0, only
    this rank's shards included (ZeRO-style seal — W seals jointly
    cover the leaf exactly once). Everything else is sealed in full by
    every rank (replication comes free and any single survivor can
    restore it)."""
    import cloudpickle

    t0 = time.perf_counter()
    paths, leaves, treedef = tree_paths_and_leaves(state)
    # device plane on: jax leaves stay device-resident and seal as
    # device frames — no device_get bounce, no host copy of the payload
    # (shard slices cut on device along the SAME np.array_split
    # boundaries, so mixed host/device seal waves regather identically)
    device_seal = _seal_leaves_device()
    if not device_seal:
        leaves = _host_leaves(leaves)
    owned = [v for v in range(virtual_shards) if v % world == rank]
    full: Dict[int, Any] = {}
    sharded: Dict[int, Dict[int, Any]] = {}
    for i, (path, leaf) in enumerate(zip(paths, leaves)):
        if isinstance(leaf, np.ndarray) or hasattr(leaf, "shape"):
            arr = leaf
        else:
            arr = np.asarray(leaf)
        shardable = (
            _matches_any(path, elastic_shard_rules)
            and getattr(arr, "ndim", 0) >= 1
            and arr.shape[0] >= virtual_shards
        )
        if shardable:
            if device_seal and not isinstance(arr, np.ndarray):
                # device-side cuts: each slice is its own device buffer
                # the pickler exports as one frame
                sizes = _split_sizes(arr.shape[0], virtual_shards)
                offs = [0]
                for s in sizes:
                    offs.append(offs[-1] + s)
                sharded[i] = {
                    v: arr[offs[v] : offs[v + 1]] for v in owned
                }
            else:
                host = (
                    arr
                    if isinstance(arr, np.ndarray)
                    else np.asarray(arr)
                )
                slices = np.array_split(host, virtual_shards, axis=0)
                sharded[i] = {
                    v: np.ascontiguousarray(slices[v]) for v in owned
                }
        else:
            full[i] = leaf
    payload = {
        "step": int(step),
        "rank": int(rank),
        "world": int(world),
        "vshards": int(virtual_shards),
        "paths": paths,
        "treedef": cloudpickle.dumps(treedef),
        "full": full,
        "sharded": sharded,
    }
    from ray_tpu.cluster import worker as worker_mod

    hex_id = worker_mod.seal_local_value(payload, owner=owner)
    if hex_id is None:
        # not inside a cluster worker (local/in-process runtime): plain
        # put; pin the ref so the object outlives this frame
        ref = ray_tpu.put(payload)
        _LOCAL_SEAL_REFS[ref.hex] = ref
        hex_id = ref.hex
    nbytes = sum(
        getattr(x, "nbytes", 0)
        if hasattr(x, "nbytes")
        else getattr(np.asarray(x), "nbytes", 0)
        for x in full.values()
    ) + sum(
        s.nbytes for d in sharded.values() for s in d.values()
    )
    ELASTIC_SEAL_BYTES.inc(nbytes)
    ELASTIC_SEAL_MS.observe((time.perf_counter() - t0) * 1e3)
    return hex_id, owned


def fetch_sealed(
    hex_id: str, timeout: float = 60.0, land: str = "device"
) -> Any:
    """Fetch one sealed state payload: inside a worker the pull lands
    in the local arena (second directory location = replication);
    driver-side it rides the client's located-get (socket plane).
    ``land="device"`` (default) lands device-frame leaves back as
    ``jax.Array`` with one device_put straight from the arena view —
    the regather then concatenates on device; ``land="host"`` keeps the
    pre-device-plane host views (pure replication pulls)."""
    from ray_tpu.cluster import worker as worker_mod

    if getattr(worker_mod, "_CURRENT_WORKER", None) is not None:
        return worker_mod.fetch_into_local_arena(
            hex_id, timeout=timeout, land=land
        )
    from ray_tpu.cluster.device_plane import landing
    from ray_tpu.core.object_store import ObjectRef

    with landing(land):
        return ray_tpu.get(ObjectRef.weak(hex_id), timeout=timeout)


def regather_state(payloads: List[dict]) -> Tuple[Any, int]:
    """Rebuild the full state pytree from sealed payloads (any order,
    any mix of old ranks). Returns (state, step). All payloads must
    come from one seal wave (same step); sharded leaves need full virtual
    coverage across the payload set."""
    import cloudpickle
    import jax

    if not payloads:
        raise ElasticStateIncomplete("no sealed payloads to regather")
    steps = {int(p["step"]) for p in payloads}
    if len(steps) != 1:
        raise ElasticStateIncomplete(
            f"mixed-step seal set {sorted(steps)}; refuse to frankenstein"
        )
    ref0 = payloads[0]
    vshards = int(ref0["vshards"])
    n_leaves = len(ref0["paths"])
    treedef = cloudpickle.loads(ref0["treedef"])
    leaves: List[Any] = [None] * n_leaves
    for i in range(n_leaves):
        for p in payloads:
            if i in p["full"]:
                leaves[i] = p["full"][i]
                break
        if leaves[i] is not None:
            continue
        pieces: Dict[int, Any] = {}
        for p in payloads:
            pieces.update(p["sharded"].get(i, {}))
        if len(pieces) != vshards:
            raise ElasticStateIncomplete(
                f"leaf {ref0['paths'][i]}: virtual shards "
                f"{sorted(pieces)} of {vshards} available"
            )
        ordered = [pieces[v] for v in range(vshards)]
        if any(isinstance(x, jax.Array) for x in ordered):
            # device-landed shards: concatenate ON DEVICE — the only
            # host hop the device plane leaves in the regather is gone
            # (restore's device_put of a jax.Array is a device-side
            # reshard). Bit-exact: concat moves raw buffers.
            import jax.numpy as jnp

            leaves[i] = jnp.concatenate(ordered, axis=0)
        else:
            leaves[i] = np.concatenate(ordered, axis=0)
    return jax.tree.unflatten(treedef, leaves), int(ref0["step"])


# ---------------------------------------------------------------------------
# rank actor (one elastic worker; rank/world assigned per generation)
# ---------------------------------------------------------------------------


@ray_tpu.remote
class _ElasticRank:
    def __init__(self, gang_id: str, experiment_name: str, trial_dir: str):
        self.gang_id = gang_id
        self.experiment_name = experiment_name
        self.trial_dir = trial_dir

    def ping(self) -> bool:
        return True

    def run_generation(self, payload: dict) -> dict:
        """Run the managed step loop for one gang generation.

        Returns ``{"status": "done"|"reshape", "step", "seal"
        {"hex","vidx","step"}, "reports", "world", "rank"}``. Exits with
        "reshape" (after sealing the exact boundary state) the moment a
        collective reports the epoch fenced; the driver regathers and
        re-launches."""
        from concurrent.futures import ThreadPoolExecutor

        rank = int(payload["rank"])
        world = int(payload["world"])
        epoch = int(payload["epoch"])
        vshards = int(payload["virtual_shards"])
        total_steps = int(payload["total_steps"])
        seal_every = int(payload["seal_interval_steps"])
        owner = payload.get("owner", "")
        shard_rules = tuple(payload.get("elastic_shard_rules", ()))
        config = dict(payload.get("config") or {})
        gang = GangContext(
            payload["hub"],
            self.gang_id,
            rank,
            world,
            epoch,
            vshards,
        )
        ctx = TrainContext(
            world_rank=rank,
            world_size=world,
            local_rank=rank,
            experiment_name=self.experiment_name,
            trial_dir=self.trial_dir,
            gang=gang,
        )
        ctx._reports = []
        _set_context(ctx)
        seal_meta: Optional[dict] = None
        boundary_step: Optional[int] = None
        boundary_state: Any = None
        # buddy replication runs OFF the step loop: a pull against a
        # node that just died blocks until its fetch timeout, and a rank
        # wedged there can't reach the collective where the epoch fence
        # would release it — the whole gang would sit out the fetch
        # budget before reshaping
        buddy_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"elastic-buddy-r{rank}"
        )
        buddy_inflight: List[Any] = []

        def _buddy_pull(hex_id: str) -> None:
            if buddy_inflight and not buddy_inflight[0].done():
                return  # previous pull still running: skip, best-effort
            buddy_inflight.clear()
            buddy_inflight.append(
                buddy_pool.submit(
                    lambda: fetch_sealed(hex_id, timeout=30.0)
                )
            )

        def _seal(state, step) -> dict:
            hex_id, vidx = seal_rank_state(
                state,
                step,
                rank,
                world,
                vshards,
                elastic_shard_rules=shard_rules,
                owner=owner,
            )
            return {
                "hex": hex_id,
                "vidx": vidx,
                "step": int(step),
                "epoch": epoch,
            }

        try:
            import cloudpickle

            # fns ship BY VALUE (pipeline-install idiom): a driver-side
            # closure or test-module fn must not require the worker to
            # import the driver's module
            init_fn = cloudpickle.loads(payload["init_fn"])
            step_fn = cloudpickle.loads(payload["step_fn"])
            resume = payload.get("resume")
            if resume:
                payloads = [
                    fetch_sealed(h) for h in resume["seals"]
                ]
                state, step = regather_state(payloads)
                if step != int(resume["step"]):
                    raise ElasticStateIncomplete(
                        f"seal step {step} != resume step {resume['step']}"
                    )
            else:
                state = init_fn(config)
                step = 0
            if payload.get("shard_rules"):
                # device-level placement per the partition-rule exemplar
                from ray_tpu.parallel.mesh import MeshConfig, build_mesh

                mesh = build_mesh(MeshConfig())  # rank-local mesh
                state = apply_shard_rules(
                    state, payload["shard_rules"], mesh
                )
            boundary_step, boundary_state = step, state
            while step < total_steps:
                state, metrics = step_fn(state, step, gang, config)
                step += 1
                boundary_step, boundary_state = step, state
                with ctx._lock:
                    ctx._reports.append(
                        {"metrics": dict(metrics or {}), "checkpoint": None}
                    )
                if metrics and metrics.get("stop"):
                    # cooperative early finish (continuous-learning
                    # loops have no fixed horizon): the step fn asked to
                    # stop. The decision MUST be identical across ranks
                    # for this step (derive it from per-step-idempotent
                    # shared state), so the whole gang breaks together
                    # and every rank returns "done" at the same step.
                    break
                if (
                    seal_every
                    and step % seal_every == 0
                    and step < total_steps
                ):
                    seal_meta = _seal(state, step)
                    # buddy replication: every rank pulls its left
                    # neighbour's fresh seal into the LOCAL arena, so
                    # each seal gains a second directory location on
                    # (usually) another node before the next fault window
                    peers = gang.allgather(seal_meta)
                    if world > 1 and cfg.elastic_buddy_replicate:
                        buddy = peers[(rank - 1) % world]
                        _buddy_pull(buddy["hex"])
                    try:
                        gang.hub.note_seal.remote(
                            rank,
                            seal_meta["step"],
                            seal_meta["hex"],
                            seal_meta["vidx"],
                            epoch,
                        )
                    except Exception:  # noqa: BLE001 - registry is advisory
                        pass
            final = _seal(state, step)
            return {
                "status": "done",
                "step": step,
                "seal": final,
                "periodic": seal_meta,
                "reports": ctx._reports,
                "rank": rank,
                "world": world,
            }
        except GangEpochRevoked as exc:
            if boundary_state is None:
                # revoked before the first boundary existed (restore-time
                # fence): nothing to seal, the driver re-plans from the
                # same resume set
                raise
            logger.info(
                "gang %s rank %d: epoch %d revoked at step %d (%s)",
                self.gang_id,
                rank,
                epoch,
                boundary_step,
                exc,
            )
            broke = _seal(boundary_state, boundary_step)
            return {
                "status": "reshape",
                "step": boundary_step,
                "seal": broke,
                "periodic": seal_meta,
                "reports": ctx._reports,
                "rank": rank,
                "world": world,
            }
        finally:
            buddy_pool.shutdown(wait=False, cancel_futures=True)
            _set_context(None)


# ---------------------------------------------------------------------------
# driver: elastic worker group + trainer
# ---------------------------------------------------------------------------


@dataclass
class _Generation:
    index: int
    world: int
    epoch: int
    pg: Any
    nodes: List[str]
    actors: List[Any]
    refs: List[Any]
    seal_hexes: List[str] = field(default_factory=list)


class ElasticTrainer:
    """Driver for elastic gangs: places a generation through the
    PG/kernel path, registers membership with the head, watches the
    gang epoch, and on any membership change reshapes the mesh to the
    surviving topology, regathers state from the object plane, and
    resumes at the exact boundary step — growing back when capacity
    returns.

    ``init_fn(config) -> state`` builds the step-0 state pytree;
    ``step_fn(state, step, gang, config) -> (state, metrics)`` advances
    one step, using ``gang.allreduce_shards`` /
    ``gang.owned_shards()`` for reshape-invariant data parallelism.
    A truthy ``metrics["stop"]`` requests a cooperative early finish
    (the continuous-learning case — no fixed horizon): every rank of
    the generation must compute the same value for the same step (use
    per-step-idempotent shared state, e.g. the RL trajectory feed's
    ``stop_for_step``), and the gang seals + returns done there."""

    def __init__(
        self,
        init_fn: Callable[[Dict[str, Any]], Any],
        step_fn: Callable[..., Tuple[Any, Dict[str, Any]]],
        *,
        total_steps: int,
        elastic_config: ElasticConfig,
        train_loop_config: Optional[Dict[str, Any]] = None,
        run_config: Optional[RunConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
    ):
        self.init_fn = init_fn
        self.step_fn = step_fn
        self.total_steps = int(total_steps)
        self.elastic = elastic_config
        self.config = dict(train_loop_config or {})
        self.run_config = run_config or RunConfig()
        self.scaling = scaling_config or ScalingConfig(
            num_workers=elastic_config.max_workers
        )
        self.gang_id = f"gang-{uuid.uuid4().hex[:10]}"
        self._lock = threading.Lock()
        self._resize_request: Optional[int] = None
        self._target_world = self.elastic.world_for(
            self.elastic.max_workers
        )
        self._hub = None
        self._epoch = 0
        self._generation = 0
        self._progress_step = 0
        # (rank, step, epoch) -> seal entry: accumulated across registry
        # polls so complete waves survive even when ranks seal
        # asynchronously; epoch in the key keeps a replayed step from
        # mixing shards of two generations into one "wave"
        self._seal_cache: Dict[Tuple[int, int, int], dict] = {}
        # node_id -> monotonic time we OBSERVED it die; placements avoid
        # these until the head's own health verdict has certainly landed
        # (a grow right after a kill must not re-place onto the corpse)
        self._recent_dead: Dict[str, float] = {}
        self._old_generations: List[List[str]] = []
        # seals the CURRENT generation resumes from: exempt from the
        # retention window until a newer restore (or completion)
        # supersedes them
        self._resume_hexes: set = set()
        self.disk_restores = 0
        self.reshape_log: List[dict] = []

    # -- public control surface ----------------------------------------
    def request_resize(self, world: int) -> None:
        """Ask the running gang to reshape to ``world`` ranks at its
        next step boundary (fences the epoch; survivors seal + the
        driver re-plans). Thread-safe; callable mid-``fit``."""
        with self._lock:
            self._resize_request = int(world)

    def progress(self) -> dict:
        with self._lock:
            return {
                "step": self._progress_step,
                "generation": self._generation,
                "epoch": self._epoch,
                "world": self._target_world,
            }

    # -- capacity -------------------------------------------------------
    def _worker_res(self) -> Dict[str, float]:
        res = dict(self.elastic.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.scaling.use_tpu:
            res.setdefault("TPU", 1.0)
        return res

    def _placeable_ranks(self, exclude_nodes: Sequence[str] = ()) -> int:
        """Advisory capacity probe (the PG itself rechecks): how many
        worker bundles the currently-advertised free capacity holds.
        Under a STRICT_SPREAD gang the unit is spread-feasible NODES,
        not aggregate CPUs — one big surviving node must read as
        capacity for ONE rank, so the gang shrinks to the surviving
        topology instead of parking on an infeasible aggregate.
        ``exclude_nodes`` lets the grow probe discount nodes already
        hosting ranks (a strict-spread gang can't grow onto its own
        hosts; counting them made every world-1 generation flap)."""
        res = self._worker_res()
        excl = set(exclude_nodes)
        try:
            if self.elastic.placement_strategy == "STRICT_SPREAD":
                hosts = 0
                for n in ray_tpu.nodes():
                    if not n.get("Alive") or n.get("NodeID") in excl:
                        continue
                    avail = n.get("Available") or n.get("Resources") or {}
                    if all(
                        avail.get(k, 0.0) >= v
                        for k, v in res.items()
                        if v > 0
                    ):
                        hosts += 1
                return hosts
            avail = ray_tpu.available_resources()
        except Exception:  # noqa: BLE001
            return 0
        counts = [
            int(avail.get(k, 0.0) // v) for k, v in res.items() if v > 0
        ]
        return min(counts) if counts else 0

    # -- generation lifecycle ------------------------------------------
    def _is_remote(self) -> bool:
        from ray_tpu.core.runtime import get_runtime

        return bool(getattr(get_runtime(), "is_remote", False))

    def _runtime(self):
        from ray_tpu.core.runtime import get_runtime

        return get_runtime()

    def _avoid_now(self) -> List[str]:
        horizon = max(30.0, 2.0 * float(cfg.health_timeout_s))
        now = time.monotonic()
        self._recent_dead = {
            n: t for n, t in self._recent_dead.items() if now - t < horizon
        }
        return sorted(self._recent_dead)

    def _place(self, world: int, avoid: List[str]):
        from ray_tpu.core.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        res = self._worker_res()
        pg = ray_tpu.placement_group(
            [dict(res)] * world,
            strategy=self.elastic.placement_strategy,
            avoid_nodes=avoid,
        )
        if not pg.wait(timeout_seconds=float(cfg.elastic_place_wait_s)):
            try:
                ray_tpu.remove_placement_group(pg)
            except Exception:  # noqa: BLE001
                pass
            raise TimeoutError(
                f"elastic gang: PG for {world} x {res} not schedulable"
            )
        if self._is_remote():
            nodes = self._runtime().wait_placement_group(pg.id, timeout=30)
        else:
            nodes = [b.node_id or "" for b in pg._state.bundles]
        name = self.run_config.name or self.gang_id
        trial_dir = ""
        actors = [
            _ElasticRank.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i
                ),
                resources={},
            ).remote(self.gang_id, name, trial_dir)
            for i in range(world)
        ]
        return pg, nodes, actors

    def _register(self, nodes: List[str]) -> int:
        members = {r: n for r, n in enumerate(nodes)}
        if self._is_remote():
            self._epoch = self._runtime().gang_register(
                self.gang_id,
                members,
                min_size=self.elastic.min_workers,
                epoch_floor=self._epoch,
                # elasticity plane (PR 19): declare the grow-back want
                # and per-rank shape so the unified controller can put
                # this gang's deficit into its demand matrix
                want_world=self.elastic.world_for(
                    self.elastic.max_workers
                ),
                resources_per_rank=self._worker_res(),
                grow=bool(self.elastic.grow),
            )
        else:
            self._epoch += 1
        return self._epoch

    def _ensure_hub(self, epoch: int, world: int):
        Hub = ray_tpu.remote(_GangHubActor)
        if self._hub is not None:
            try:
                ray_tpu.get(
                    self._hub.configure.remote(epoch, world), timeout=15
                )
                return self._hub
            except Exception:  # noqa: BLE001 - hub died with its node
                self._hub = None
        self._hub = Hub.remote(self.gang_id, epoch, world)
        ray_tpu.get(self._hub.configure.remote(epoch, world), timeout=60)
        return self._hub

    def _fence(self, reason: str) -> None:
        if self._is_remote():
            try:
                # monotone guard: a failed-over head that lost the
                # (ephemeral) gang table answers 0 — never let that
                # regress the driver epoch, or (rank, step, epoch) seal
                # keys could collide across a failover boundary
                self._epoch = max(
                    self._epoch + 1,
                    self._runtime().gang_fence(
                        self.gang_id, reason=reason
                    ),
                )
            except Exception:  # noqa: BLE001 - head blip; hub fence still lands
                self._epoch += 1
        else:
            self._epoch += 1
        if self._hub is not None:
            try:
                self._hub.set_epoch.remote(self._epoch)
            except Exception:  # noqa: BLE001
                pass

    # -- watch loop -----------------------------------------------------
    def _watch(self, gen: _Generation) -> Tuple[Dict[int, dict], Dict[int, BaseException]]:
        results: Dict[int, dict] = {}
        errors: Dict[int, BaseException] = {}
        ref_rank = {r.hex: i for i, r in enumerate(gen.refs)}
        pending = list(gen.refs)
        fenced_at: Optional[float] = None
        last_grow_probe = 0.0
        killed_dead: set = set()
        # head epoch watcher: ONE long-poll rides the head's GangSync
        # cond-wait (returns at RPC latency after any bump) instead of
        # hammering the head with zero-timeout polls every loop pass
        sync_box: Dict[str, Any] = {}
        sync_stop = threading.Event()

        def _sync_loop() -> None:
            seen_epoch = gen.epoch
            dead: set = set()
            while not sync_stop.is_set():
                try:
                    reply = self._runtime().gang_sync(
                        self.gang_id,
                        seen_epoch,
                        timeout=float(cfg.gang_sync_max_wait_s),
                    )
                except Exception:  # noqa: BLE001 - head blip
                    sync_stop.wait(0.5)
                    continue
                if reply.get("epoch", 0) > seen_epoch:
                    # keep polling past the first bump: a SECOND node
                    # death during the drain window bumps again and
                    # names more dead ranks — without a live watcher
                    # those corpses would sit out the whole
                    # fence_drain_s budget. Dead ranks accumulate so a
                    # bump the watch loop hasn't consumed yet is never
                    # overwritten away.
                    seen_epoch = reply["epoch"]
                    dead.update(int(r) for r in reply.get("dead_ranks", ()))
                    sync_box["reply"] = dict(
                        reply, dead_ranks=sorted(dead)
                    )
                    continue
                if not reply.get("epoch"):
                    # unknown gang (head failed over and lost the
                    # ephemeral table): replies come back instantly, so
                    # pace the loop instead of hammering the recovering
                    # head; the next generation re-registers
                    sync_stop.wait(2.0)

        if self._is_remote():
            threading.Thread(
                target=_sync_loop, daemon=True, name="gang-sync"
            ).start()
        while pending:
            done, pending = ray_tpu.wait(
                pending, num_returns=len(pending), timeout=0.5
            )
            for ref in done:
                rank = ref_rank[ref.hex]
                try:
                    results[rank] = ray_tpu.get(ref, timeout=30)
                    logger.debug(
                        "gang %s: rank %d returned %s at step %s",
                        self.gang_id,
                        rank,
                        results[rank].get("status"),
                        results[rank].get("step"),
                    )
                except Exception as exc:  # noqa: BLE001 - rank died
                    logger.debug(
                        "gang %s: rank %d ref failed: %r",
                        self.gang_id,
                        rank,
                        exc,
                    )
                    errors[rank] = exc
            broke = bool(errors) or any(
                r.get("status") == "reshape" for r in results.values()
            )
            # head is the epoch authority: mirror bumps into the hub so
            # survivors break at their next collective
            if self._is_remote():
                try:
                    reply = sync_box.get("reply")
                    if (
                        reply is not None
                        and reply["epoch"] > gen.epoch
                        and self._hub is not None
                    ):
                        self._epoch = max(self._epoch, reply["epoch"])
                        self._hub.set_epoch.remote(reply["epoch"])
                        if fenced_at is None:
                            logger.debug(
                                "gang %s: head epoch %d > %d "
                                "(dead ranks %s); hub fenced",
                                self.gang_id,
                                reply["epoch"],
                                gen.epoch,
                                reply.get("dead_ranks"),
                            )
                        fenced_at = fenced_at or time.monotonic()
                        # the head named the dead ranks: kill their
                        # actors NOW so the pending run_generation refs
                        # fail fast (a SIGKILLed node's in-flight direct
                        # call otherwise sits out the whole drain budget
                        # waiting for a result push that can never come)
                        for r in reply.get("dead_ranks", ()):  # noqa: B007
                            r = int(r)
                            if (
                                r < len(gen.actors)
                                and r not in killed_dead
                                and r not in results
                                and r not in errors
                            ):
                                killed_dead.add(r)
                                self._kill_quiet(gen.actors[r])
                except Exception:  # noqa: BLE001 - head blip
                    pass
            # seal-registry cache for restore (survives hub death)
            if self._hub is not None and pending:
                try:
                    reg = ray_tpu.get(
                        self._hub.seal_registry.remote(), timeout=10
                    )
                    dropped: List[str] = []
                    with self._lock:
                        for r, entries in reg.items():
                            for e in entries:
                                self._seal_cache[
                                    (
                                        int(r),
                                        int(e["step"]),
                                        int(e.get("epoch", -1)),
                                    )
                                ] = e
                        if self._seal_cache:
                            self._progress_step = max(
                                self._progress_step,
                                max(
                                    s for _, s, _ in self._seal_cache
                                ),
                            )
                            # bounded: keep the newest few steps only;
                            # waves falling off the window retire, so a
                            # long run's periodic seals don't pin the
                            # arenas forever
                            keep = set(
                                sorted(
                                    {s for _, s, _ in self._seal_cache},
                                    reverse=True,
                                )[:8]
                            )
                            dropped = [
                                v["hex"]
                                for k, v in self._seal_cache.items()
                                if k[1] not in keep
                            ]
                            self._seal_cache = {
                                k: v
                                for k, v in self._seal_cache.items()
                                if k[1] in keep
                            }
                    if dropped:
                        self._retire_seals(dropped)
                except Exception:  # noqa: BLE001
                    pass
            # resize requests + grow-back probe fence the gang
            with self._lock:
                resize = self._resize_request
            if resize is not None and not broke and fenced_at is None:
                self._target_world = self.elastic.world_for(resize)
                self._fence("resize")
                fenced_at = time.monotonic()
                with self._lock:
                    self._resize_request = None
            now = time.monotonic()
            if (
                cfg.elastic_controller
                and self._is_remote()
                and not broke
                and fenced_at is None
                and now - last_grow_probe >= float(cfg.elastic_grow_poll_s)
            ):
                # unified elasticity plane (PR 19): the controller's
                # solver verdict replaces the local capacity probe —
                # grow when it says more ranks are placeable, CEDE when
                # serve pressure outbid this gang for its nodes (a
                # graceful reshape to the hinted world: seals + refit,
                # no attempts burned, no disk restore). hint=None means
                # no verdict yet: fall through to the legacy probe so
                # the gang never stalls on a cold controller.
                last_grow_probe = now
                hint = None
                try:
                    reply = self._runtime().gang_hint(self.gang_id)
                    hint = reply.get("world_hint")
                except Exception:  # noqa: BLE001 - head blip
                    hint = None
                if hint is not None:
                    hinted = self.elastic.world_for(
                        max(int(hint), self.elastic.min_workers)
                    )
                    if hinted > gen.world and self.elastic.grow:
                        self._target_world = hinted
                        self._fence("grow")
                        fenced_at = time.monotonic()
                    elif hinted < gen.world:
                        self._target_world = hinted
                        self._fence("cede")
                        fenced_at = time.monotonic()
                else:
                    last_grow_probe = 0.0  # legacy probe may run now
            if (
                self.elastic.grow
                and not broke
                and fenced_at is None
                and gen.world < self.elastic.world_for(self.elastic.max_workers)
                and now - last_grow_probe >= float(cfg.elastic_grow_poll_s)
            ):
                last_grow_probe = now
                grown = self.elastic.world_for(
                    gen.world
                    + self._placeable_ranks(
                        exclude_nodes=list(gen.nodes) + self._avoid_now()
                    )
                )
                if grown > gen.world:
                    self._target_world = grown
                    self._fence("grow")
                    fenced_at = time.monotonic()
            if broke and fenced_at is None:
                # rank-level break the head can't see (actor kill /
                # rendezvous timeout): fence so survivors stop waiting
                # on the corpse
                self._fence("break")
                fenced_at = time.monotonic()
            if (
                fenced_at is not None
                and pending
                and time.monotonic() - fenced_at
                > float(self.elastic.fence_drain_s)
            ):
                # a straggler wedged in user code past the drain budget:
                # kill it; its ref resolves to ActorDied next pass
                for ref in pending:
                    try:
                        ray_tpu.kill(gen.actors[ref_rank[ref.hex]])
                    except Exception:  # noqa: BLE001
                        pass
                fenced_at = time.monotonic()  # one more drain window
        sync_stop.set()
        return results, errors

    # -- restore selection ---------------------------------------------
    def _coverage_ok(self, entries: List[dict]) -> bool:
        """Do these seals jointly cover the virtual grid? (metadata
        only; sharded leaves need every vidx, replicated need any)"""
        vshards = self.elastic.virtual_shards or self.elastic.max_workers
        if not entries:
            return False
        if not self.elastic.elastic_shard_rules:
            return True
        covered = set()
        for e in entries:
            covered.update(e.get("vidx") or ())
        return covered >= set(range(vshards))

    def _live_hexes(self, hexes: List[str]) -> List[str]:
        """Filter to seals the object directory still resolves."""
        if not self._is_remote():
            return [h for h in hexes if h in _LOCAL_SEAL_REFS]
        from ray_tpu.core.object_store import ObjectRef

        sizes = self._runtime().object_sizes(
            [ObjectRef.weak(h) for h in hexes]
        )
        locs = self._runtime().object_locations(
            [ObjectRef.weak(h) for h in hexes]
        )
        return [h for h in hexes if sizes.get(h, 0) > 0 or locs.get(h)]

    def _pick_restore(
        self,
        results: Dict[int, dict],
    ) -> Tuple[Optional[dict], List[str]]:
        """Choose the freshest consistent seal set. Preference order:
        break-time boundary seals (exact step) -> last periodic seal
        wave (object plane) -> disk checkpoint (counted; the chaos gate
        asserts it never happens)."""
        by_step: Dict[int, List[dict]] = {}
        for r in results.values():
            if r.get("seal"):
                by_step.setdefault(int(r["step"]), []).append(r["seal"])
        for step in sorted(by_step, reverse=True):
            entries = by_step[step]
            hexes = self._live_hexes([e["hex"] for e in entries])
            entries = [e for e in entries if e["hex"] in hexes]
            if entries and self._coverage_ok(entries):
                return (
                    {"step": step, "seals": self._restore_hexes(entries)},
                    [e["hex"] for e in entries],
                )
        # periodic wave: driver-side registry cache (+ what survivors
        # reported); all seals of a wave share one (step, epoch) — a
        # replayed step number from a LATER generation must never mix
        # with the pre-replay generation's shards
        with self._lock:
            cache = list(self._seal_cache.values())
        for r in results.values():
            if r.get("periodic"):
                cache.append(r["periodic"])
        waves: Dict[Tuple[int, int], Dict[str, dict]] = {}
        for e in cache:
            key = (int(e["step"]), int(e.get("epoch", -1)))
            waves.setdefault(key, {})[e["hex"]] = e
        for step, _epoch in sorted(waves, reverse=True):
            entries = list(waves[(step, _epoch)].values())
            hexes = self._live_hexes([e["hex"] for e in entries])
            entries = [e for e in entries if e["hex"] in hexes]
            if entries and self._coverage_ok(entries):
                return (
                    {"step": step, "seals": self._restore_hexes(entries)},
                    [e["hex"] for e in entries],
                )
        return None, []

    def _restore_hexes(self, entries: List[dict]) -> List[str]:
        # fully-replicated state: one seal restores everything; sharded
        # state needs the whole set
        if not self.elastic.elastic_shard_rules:
            return [entries[0]["hex"]]
        return [e["hex"] for e in entries]

    # -- cleanup --------------------------------------------------------
    def _teardown_generation(self, actors: List[Any], pg: Any) -> None:
        from .trainer import kill_actors_bounded

        kill_actors_bounded(actors, 10.0)
        try:
            ray_tpu.remove_placement_group(pg)
        except Exception:  # noqa: BLE001 - head blip; expiry sweep covers
            pass

    @staticmethod
    def _kill_quiet(actor) -> None:
        try:
            ray_tpu.kill(actor)
        except Exception:  # noqa: BLE001
            pass

    def _retire_seals(self, hexes: List[str]) -> None:
        """Keep ``keep_generations`` seal waves; free older ones.

        Two guards keep the retention window from eating the only
        restorable state: a re-picked wave (consecutive failed
        generations restoring from the same seals) MOVES to the newest
        slot instead of duplicating until it marches itself off the
        window, and a wave the current generation is actively resuming
        from (``_resume_hexes``) is never freed, however old."""
        if hexes:
            wave = list(hexes)
            self._old_generations = [
                w for w in self._old_generations if set(w) != set(wave)
            ]
            self._old_generations.append(wave)
        keep = max(1, int(self.elastic.keep_generations))
        idx = 0
        while len(self._old_generations) - idx > keep:
            if self._resume_hexes & set(self._old_generations[idx]):
                idx += 1
                continue
            dead = self._old_generations.pop(idx)
            if self._is_remote():
                try:
                    self._runtime().free_objects(dead)
                except Exception:  # noqa: BLE001
                    pass
            else:
                for h in dead:
                    _LOCAL_SEAL_REFS.pop(h, None)

    def _disk_restore(self) -> Tuple[Optional[dict], List[str]]:
        """Last resort: object plane has no coverage (e.g. every seal
        holder died simultaneously). Counted — the chaos acceptance
        gate asserts this stays at zero. The checkpoint's state is
        re-sealed as an ordinary full payload so the rank-side restore
        path stays uniform."""
        import os

        name = self.run_config.name or self.gang_id
        storage = self.run_config.storage_path
        if not storage:
            return None, []
        trial_dir = os.path.join(storage, name)
        from .trainer import JaxTrainer

        path = JaxTrainer._latest_checkpoint_path(trial_dir)
        if path is None:
            return None, []
        ELASTIC_DISK_RESTORES.inc()
        self.disk_restores += 1
        blob = Checkpoint(path).load_state()
        step = int(blob.get("elastic_step", 0))
        vshards = self.elastic.virtual_shards or self.elastic.max_workers
        hex_id, _ = seal_rank_state(
            blob["state"], step, 0, 1, vshards, elastic_shard_rules=()
        )
        return {"step": step, "seals": [hex_id]}, [hex_id]

    # -- the main loop --------------------------------------------------
    def fit(self) -> Result:
        import cloudpickle

        owner = ""
        if self._is_remote():
            owner = getattr(self._runtime(), "client_id", "")
        vshards = self.elastic.virtual_shards or self.elastic.max_workers
        seal_every = (
            self.elastic.seal_interval_steps
            if self.elastic.seal_interval_steps is not None
            else int(cfg.elastic_seal_interval_steps)
        )
        resume: Optional[dict] = None
        all_reports: List[Dict[str, Any]] = []
        backoff = 0.2
        place_start: Optional[float] = None
        error: Optional[BaseException] = None
        final_state_seal: List[str] = []
        while True:
            world = self._target_world
            if world < max(1, self.elastic.min_workers):
                raise RuntimeError(
                    f"elastic gang below min_workers "
                    f"({world} < {self.elastic.min_workers})"
                )
            t_place = time.monotonic()
            t_place_wall = time.time()
            try:
                pg, nodes, actors = self._place(world, self._avoid_now())
            except TimeoutError:
                # unschedulable right now (mid-backfill): shrink toward
                # what fits, or park with backoff up to the deadline
                if place_start is None:
                    place_start = time.monotonic()
                elif (
                    time.monotonic() - place_start
                    > float(self.elastic.place_deadline_s)
                ):
                    error = RuntimeError(
                        f"elastic gang unplaceable for "
                        f"{self.elastic.place_deadline_s}s at world={world}"
                    )
                    break
                placeable = self.elastic.world_for(
                    self._placeable_ranks(exclude_nodes=self._avoid_now())
                )
                if placeable >= max(1, self.elastic.min_workers):
                    self._target_world = placeable
                else:
                    time.sleep(backoff)
                    backoff = min(5.0, backoff * 1.7)
                continue
            backoff = 0.2
            place_start = None
            # reshape-phase span (ISSUE 15): one slice per placement in
            # the Chrome-trace export, beside the generation slices
            SPANS.record(
                "elastic_place",
                "elastic",
                t_place_wall,
                time.monotonic() - t_place,
                pid=f"gang:{self.gang_id[:8]}",
                world=world,
                generation=self._generation,
            )
            try:
                epoch = self._register(nodes)
                hub = self._ensure_hub(epoch, world)
                start_step = resume["step"] if resume else 0
                payload_base = {
                    "world": world,
                    "epoch": epoch,
                    "virtual_shards": vshards,
                    "total_steps": self.total_steps,
                    "seal_interval_steps": seal_every,
                    "owner": owner,
                    "elastic_shard_rules": list(
                        self.elastic.elastic_shard_rules
                    ),
                    "shard_rules": list(self.elastic.shard_rules),
                    "config": self.config,
                    "hub": hub,
                    "init_fn": cloudpickle.dumps(self.init_fn),
                    "step_fn": cloudpickle.dumps(self.step_fn),
                    "resume": resume,
                }
                refs = [
                    a.run_generation.remote(dict(payload_base, rank=r))
                    for r, a in enumerate(actors)
                ]
                gen = _Generation(
                    index=self._generation,
                    world=world,
                    epoch=epoch,
                    pg=pg,
                    nodes=nodes,
                    actors=actors,
                    refs=refs,
                )
                logger.info(
                    "gang %s gen %d: world=%d epoch=%d nodes=%s start=%d",
                    self.gang_id,
                    gen.index,
                    world,
                    epoch,
                    nodes,
                    start_step,
                )
                t_watch = time.monotonic()
                t_watch_wall = time.time()
                results, errors = self._watch(gen)
            except BaseException:
                # a failure between placement and drain (head blip
                # mid-register, hub spawn death, transport error on
                # submit) must not leak the bundle reservation or the
                # world's actors: a caller that catches and re-runs
                # fit() would find the capacity still consumed and the
                # gang unplaceable
                self._teardown_generation(actors, pg)
                raise
            t_drain = time.monotonic()
            self._teardown_generation(gen.actors, gen.pg)
            SPANS.record(
                "elastic_generation",
                "elastic",
                t_watch_wall,
                t_drain - t_watch,
                pid=f"gang:{self.gang_id[:8]}",
                generation=gen.index,
                world=world,
                epoch=gen.epoch,
            )
            logger.info(
                "gang %s gen %d: drained in %.2fs, teardown %.2fs "
                "(%d results, %d errors)",
                self.gang_id,
                gen.index,
                t_drain - t_watch,
                time.monotonic() - t_drain,
                len(results),
                len(errors),
            )
            done = [
                r for r in results.values() if r.get("status") == "done"
            ]
            # rank 0's reports are authoritative, but when rank 0 died
            # with its node (or broke a step earlier than a peer) a
            # survivor's are the next best thing — a hole in the metric
            # stream is worse than a neighbour's view of the same
            # shared-state step. Ranks skew at most one collective, so
            # take the longest stream, rank 0 winning ties.
            rep_src = min(
                results.items(),
                key=lambda kv: (-len(kv[1].get("reports") or ()), kv[0]),
                default=(None, None),
            )[1]
            if rep_src and rep_src.get("reports"):
                all_reports.extend(rep_src["reports"])
            with self._lock:
                self._progress_step = max(
                    self._progress_step,
                    max(
                        (int(r["step"]) for r in results.values()),
                        default=self._progress_step,
                    ),
                )
            if len(done) == world and not errors:
                final_wave = sorted(
                    (r["rank"], r["seal"]["hex"]) for r in done
                )
                final_state_seal = [h for _, h in final_wave]
                self._resume_hexes = set()
                self._retire_seals(list(final_state_seal))
                break
            # ---- reshape path ----
            t_reshape = time.monotonic()
            t_reshape_wall = time.time()
            dead_nodes = sorted(
                {
                    nodes[r]
                    for r in errors
                    if r < len(nodes) and nodes[r]
                }
            )
            for n in dead_nodes:
                self._recent_dead[n] = time.monotonic()
            resume, used_hexes = self._pick_restore(results)
            if resume is None:
                resume, used_hexes = self._disk_restore()
            if resume is None:
                error = RuntimeError(
                    f"gang {self.gang_id}: no restorable state "
                    f"(errors={ {r: repr(e) for r, e in errors.items()} })"
                )
                break
            self._resume_hexes = set(used_hexes)
            # the restore point can sit BELOW steps already reported
            # (e.g. a dead rank's boundary shards only exist in an older
            # periodic wave): those steps replay, so drop their reports
            # — exactly one report per step survives (all_reports[i] is
            # step i's report)
            del all_reports[int(resume["step"]):]
            # the old generation is torn down, so advertised free
            # capacity IS the whole surviving topology: reshape to what
            # fits now (the watch loop grows back toward max_workers
            # once the autoscaler restores capacity). Nodes we OBSERVED
            # die are excluded explicitly — survivors usually break
            # faster than the head's health verdict lands, and counting
            # the corpse would call this reshape "flat" and park the
            # next placement against a dead agent
            target = self.elastic.world_for(
                max(self._target_world, self.elastic.min_workers)
            )
            cap = self.elastic.world_for(
                self._placeable_ranks(exclude_nodes=self._avoid_now())
            )
            next_world = target
            if 0 < cap < target:
                next_world = max(
                    cap,
                    self.elastic.world_for(max(1, self.elastic.min_workers)),
                )
            if next_world < 1:
                next_world = self.elastic.world_for(
                    max(1, self.elastic.min_workers)
                )
            direction = (
                "grow"
                if next_world > world
                else ("shrink" if next_world < world else "flat")
            )
            self._target_world = next_world
            ELASTIC_RESHAPES.inc(labels={"direction": direction})
            SPANS.record(
                "elastic_reshape",
                "elastic",
                t_reshape_wall,
                time.monotonic() - t_reshape,
                pid=f"gang:{self.gang_id[:8]}",
                direction=direction,
                from_world=world,
                to_world=next_world,
                resume_step=int(resume["step"]),
                dead_nodes=len(dead_nodes),
            )
            self.reshape_log.append(
                {
                    "generation": gen.index,
                    "epoch": gen.epoch,
                    "from_world": world,
                    "to_world": next_world,
                    "resume_step": resume["step"],
                    "direction": direction,
                    "dead_nodes": dead_nodes,
                }
            )
            logger.info(
                "gang %s: reshape %s %d -> %d, resume at step %d",
                self.gang_id,
                direction,
                world,
                next_world,
                resume["step"],
            )
            self._retire_seals(used_hexes)
            self._generation += 1
        # ---- final result ----
        if self._hub is not None:
            self._kill_quiet(self._hub)
            self._hub = None
        if self._is_remote():
            try:
                self._runtime().gang_unregister(self.gang_id)
            except Exception:  # noqa: BLE001
                pass
        metrics = dict(all_reports[-1]["metrics"]) if all_reports else {}
        metrics["elastic"] = {
            "generations": self._generation + 1,
            "reshapes": list(self.reshape_log),
            "disk_restores": self.disk_restores,
            "final_world": self._target_world,
        }
        checkpoint = None
        path = ""
        if error is None and self.run_config.storage_path:
            import os

            name = self.run_config.name or self.gang_id
            trial_dir = os.path.join(self.run_config.storage_path, name)
            os.makedirs(trial_dir, exist_ok=True)
            hexes = (
                final_state_seal
                if self.elastic.elastic_shard_rules
                else final_state_seal[:1]
            )
            state, step = regather_state(
                [fetch_sealed(h) for h in hexes]
            )
            checkpoint = Checkpoint.from_state(
                {"state": state, "elastic_step": step},
                os.path.join(trial_dir, f"checkpoint_{step:06d}"),
            )
            path = trial_dir
        return Result(
            metrics=metrics,
            checkpoint=checkpoint,
            path=path,
            error=error,
            metrics_history=[r["metrics"] for r in all_reports],
        )

    def final_state(self) -> Any:
        """Driver-side regather of the last sealed state (object-plane
        fetch over the socket plane; no disk involved)."""
        if not self._old_generations:
            raise RuntimeError("no sealed state (fit() not finished?)")
        hexes = self._old_generations[-1]
        payloads = [fetch_sealed(h) for h in hexes]
        state, _ = regather_state(payloads)
        return state
