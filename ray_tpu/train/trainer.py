"""JaxTrainer: controller + worker-group execution with fault tolerance.

Reference shape: train/v2 controller & worker group
(/root/reference/python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:113) and JaxTrainer/JaxConfig (train/v2/jax/). Workers are
actors gang-placed via a placement group; each runs the user's
train_loop_per_worker with a TrainContext carrying rank/world info and the
restore checkpoint. On worker failure the whole group restarts from the
latest reported checkpoint (FailurePolicy semantics).
"""
from __future__ import annotations

import os
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.scheduling_strategies import PlacementGroupSchedulingStrategy
from .checkpoint import Checkpoint
from .session import TrainContext, _set_context


@dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res


@dataclass
class FailureConfig:
    # ray parity: -1 means retry forever (elastic/chaos runs where the
    # cluster is expected to heal); 0 means fail on the first error
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)


@dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


def kill_actors_bounded(actors, deadline_s: float) -> None:
    """Best-effort parallel kill of a worker group under ONE wall-clock
    deadline. Runs on daemon threads, not a pool: a kill RPC that
    wedges past the deadline is simply abandoned — a daemon thread
    can't block interpreter exit, and an infinite-retry trainer doesn't
    accrue stuck pool threads across attempts."""
    import threading

    def _kill(w):
        try:
            ray_tpu.kill(w)
        except Exception:  # noqa: BLE001
            pass

    threads = [
        threading.Thread(
            target=_kill, args=(w,), daemon=True,
            name="train-teardown-kill",
        )
        for w in actors
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + float(deadline_s)
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))


@ray_tpu.remote
class _TrainWorker:
    """One rank of the worker group."""

    def __init__(self, rank: int, world_size: int, experiment_name: str,
                 trial_dir: str):
        self.ctx = TrainContext(
            world_rank=rank,
            world_size=world_size,
            local_rank=rank,
            experiment_name=experiment_name,
            trial_dir=trial_dir,
        )
        # Multi-host wiring (jax.distributed coordinator env) — parity with
        # JaxConfig._setup_jax_distributed_environment; in-process runtime
        # runs all ranks in one host so initialization is a no-op here.
        os.environ.setdefault("RAY_TPU_WORLD_SIZE", str(world_size))

    def run(self, fn: Callable, config: Dict[str, Any],
            restore: Optional[str],
            dataset_shards: Optional[Dict[str, Any]] = None,
            ) -> List[Dict[str, Any]]:
        self.ctx.latest_checkpoint = (
            Checkpoint(restore) if restore else None
        )
        self.ctx.dataset_shards = dict(dataset_shards or {})
        self.ctx._reports = []
        _set_context(self.ctx)
        try:
            fn(config)
        finally:
            _set_context(None)
        # checkpoints are serialized by path
        return [
            {
                "metrics": r["metrics"],
                "checkpoint": r["checkpoint"].path if r["checkpoint"] else None,
            }
            for r in self.ctx._reports
        ]


class JaxTrainer:
    """Data-parallel trainer driving a gang of workers.

    train_loop_per_worker(config) runs on every rank; use
    ray_tpu.train.get_context() / report() inside it.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable[[Dict[str, Any]], None],
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: ScalingConfig = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.config = dict(train_loop_config or {})
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        # name -> Dataset; split() into one shard per rank at launch,
        # consumed in the loop via train.get_dataset_shard(name)
        # (streaming iter_batches with prefetch — ingest overlaps the
        # train step)
        self.datasets = dict(datasets or {})

    # retry backoff bounds (class attrs so tests can shrink them):
    # decorrelated jitter keeps a persistently-unschedulable placement
    # group from hot-looping create/remove against the head
    RETRY_BACKOFF_BASE_S = 0.2
    RETRY_BACKOFF_CAP_S = 10.0

    def fit(self) -> Result:
        import random

        name = self.run_config.name or f"train-{uuid.uuid4().hex[:6]}"
        storage = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results"
        )
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)

        max_failures = self.run_config.failure_config.max_failures
        restore_path: Optional[str] = None
        attempt = 0
        rng = random.Random()
        sleep_s = self.RETRY_BACKOFF_BASE_S
        while True:
            try:
                reports = self._run_attempt(name, trial_dir, restore_path)
                return self._build_result(trial_dir, reports)
            except Exception as exc:  # noqa: BLE001
                attempt += 1
                restore_path = self._latest_checkpoint_path(trial_dir)
                # max_failures=-1: infinite retries (ray parity)
                if 0 <= max_failures < attempt:
                    return Result(
                        metrics={},
                        checkpoint=(
                            Checkpoint(restore_path) if restore_path else None
                        ),
                        path=trial_dir,
                        error=exc,
                    )
                # decorrelated jitter (AWS backoff family): sleep in
                # [base, 3*prev], capped — retries de-phase instead of
                # hammering an unschedulable PG in lockstep
                sleep_s = min(
                    self.RETRY_BACKOFF_CAP_S,
                    rng.uniform(
                        self.RETRY_BACKOFF_BASE_S, sleep_s * 3.0
                    ),
                )
                time.sleep(sleep_s)

    # teardown budget for killing the gang: a kill RPC against a node
    # that died mid-attempt can wedge past its transport retries — the
    # bundle reservation must not leak behind a hung finally
    TEARDOWN_KILL_DEADLINE_S = 10.0

    # -- internals ------------------------------------------------------
    def _run_attempt(self, name, trial_dir, restore_path):
        n = self.scaling.num_workers
        res = self.scaling.worker_resources()
        pg = ray_tpu.placement_group(
            [dict(res)] * n, strategy=self.scaling.placement_strategy
        )
        workers = []
        try:
            if not pg.wait(timeout_seconds=30):
                # raise INSIDE the try: the finally below removes the
                # pending PG — before this, an unschedulable attempt
                # leaked one parked reservation per retry
                raise TimeoutError(
                    f"placement group for {n} workers x {res} not schedulable"
                )
            # one shard per rank, split ONCE per attempt: blocks become
            # ObjectRefs here (pending ops execute through the streaming
            # shuffle plane) and only the refs ship to the workers — each
            # rank pulls its own shard's blocks over the object plane as
            # its prefetching iterator reaches them
            shard_lists = {
                dname: ds.split(n) for dname, ds in self.datasets.items()
            }
            workers = [
                _TrainWorker.options(
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=pg, placement_group_bundle_index=i
                    ),
                    resources={},  # held by the bundle reservation
                ).remote(i, n, name, trial_dir)
                for i in range(n)
            ]
            refs = [
                w.run.remote(
                    self.train_loop,
                    self.config,
                    restore_path,
                    {
                        dname: shards[i]
                        for dname, shards in shard_lists.items()
                    },
                )
                for i, w in enumerate(workers)
            ]
            reports_per_rank = ray_tpu.get(refs)
            return reports_per_rank[0]  # rank-0 reports are authoritative
        finally:
            self._teardown(workers, pg)

    def _teardown(self, workers, pg) -> None:
        """Bounded gang teardown: kills run concurrently under one
        deadline (a kill against a dead node can hang on transport
        retries), and the placement group is removed REGARDLESS — a
        wedged kill must not leak the whole bundle reservation."""
        kill_actors_bounded(workers, self.TEARDOWN_KILL_DEADLINE_S)
        try:
            ray_tpu.remove_placement_group(pg)
        except Exception:  # noqa: BLE001 - head blip; lease sweeps cover
            pass

    @staticmethod
    def _latest_checkpoint_path(trial_dir: str) -> Optional[str]:
        # Only COMPLETE checkpoints count: from_state writes
        # checkpoint_meta.json last (inside its temp dir, atomically
        # renamed into place), so its presence is the commit marker — a
        # crash mid-write must not leave a half-written directory the
        # retry loop happily restores from.
        def _complete(path: str) -> bool:
            return os.path.isdir(path) and os.path.isfile(
                os.path.join(path, "checkpoint_meta.json")
            )

        # 1. durable pointer written by train.report (works for checkpoint
        # dirs outside trial_dir too)
        pointer = os.path.join(trial_dir, "_latest_checkpoint")
        if os.path.isfile(pointer):
            with open(pointer) as f:
                path = f.read().strip()
            if _complete(path):
                return path
        # 2. fall back to the checkpoint_* naming convention inside
        # trial_dir, newest COMPLETE directory wins
        ckpts = sorted(
            d for d in os.listdir(trial_dir) if d.startswith("checkpoint_")
        ) if os.path.isdir(trial_dir) else []
        for d in reversed(ckpts):
            path = os.path.join(trial_dir, d)
            if _complete(path):
                return path
        return None

    def _build_result(self, trial_dir, reports) -> Result:
        metrics = reports[-1]["metrics"] if reports else {}
        ckpt_path = None
        for r in reversed(reports):
            if r["checkpoint"]:
                ckpt_path = r["checkpoint"]
                break
        if ckpt_path is None:
            ckpt_path = self._latest_checkpoint_path(trial_dir)
        return Result(
            metrics=metrics,
            checkpoint=Checkpoint(ckpt_path) if ckpt_path else None,
            path=trial_dir,
            metrics_history=[r["metrics"] for r in reports],
        )
