"""ray_tpu.train — distributed training orchestration.

Analog of Ray Train v2 (/root/reference/python/ray/train/v2/): a controller
creates a placement-group-gang of worker actors, wires rank/world-size
context, runs the user train loop on every worker, and restarts the group
from the latest checkpoint on failure. The compute inside the loop is
jax/pjit over the mesh (ray_tpu.parallel) — workers here are the *control*
plane, exactly the reference JaxTrainer split (train/v2/jax/jax_trainer.py:20,
config.py:44-104).
"""
from .checkpoint import Checkpoint  # noqa: F401
from .session import (  # noqa: F401
    DataIterator,
    get_context,
    get_dataset_shard,
    report,
)
from .trainer import (  # noqa: F401
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
)
from .elastic import (  # noqa: F401
    ElasticConfig,
    ElasticTrainer,
    GangContext,
    GangEpochRevoked,
    make_shard_and_gather_fns,
    match_partition_rules,
)
