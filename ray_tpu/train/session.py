"""Per-worker training session context (ray.train.get_context analog)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""
    latest_checkpoint: Optional[Checkpoint] = None
    # reporting channel back to the controller
    _reports: List[Dict[str, Any]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint


_session = threading.local()


def _set_context(ctx: Optional[TrainContext]) -> None:
    _session.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_session, "ctx", None)
    if ctx is None:
        raise RuntimeError("not inside a train worker (no session context)")
    return ctx


def report(
    metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
) -> None:
    """ray.train.report parity: record metrics (+ checkpoint) for this step."""
    import os

    ctx = get_context()
    with ctx._lock:
        ctx._reports.append(
            {"metrics": dict(metrics), "checkpoint": checkpoint}
        )
    if checkpoint is not None and ctx.trial_dir and ctx.world_rank == 0:
        # Durable pointer so the controller can restore after a crash even
        # when the checkpoint directory lives outside trial_dir.
        pointer = os.path.join(ctx.trial_dir, "_latest_checkpoint")
        tmp = pointer + ".tmp"
        with open(tmp, "w") as f:
            f.write(checkpoint.path)
        os.replace(tmp, pointer)
