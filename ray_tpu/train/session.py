"""Per-worker training session context (ray.train.get_context analog)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from .checkpoint import Checkpoint


class DataIterator:
    """Per-worker view of one dataset shard (ray.train DataIterator
    analog): ``iter_batches`` defaults to streaming ingest at
    cfg.data_prefetch_batches depth, so the train step overlaps the
    shard's object-plane pulls (and the shuffle reduce tail feeding
    them) instead of stalling between batches."""

    def __init__(self, dataset: Any):
        self._ds = dataset

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_batches: Optional[int] = None,
    ) -> Iterator[Any]:
        if prefetch_batches is None:
            from ray_tpu.config import cfg

            prefetch_batches = int(cfg.data_prefetch_batches)
        return self._ds.iter_batches(
            batch_size=batch_size,
            batch_format=batch_format,
            prefetch_batches=prefetch_batches,
        )

    def iter_rows(self) -> Iterator[Any]:
        return self._ds.iter_rows()

    def count(self) -> int:
        return self._ds.count()

    def materialize(self):
        return self._ds.materialize()


@dataclass
class TrainContext:
    world_rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_dir: str = ""
    latest_checkpoint: Optional[Checkpoint] = None
    # elastic runs: this rank's GangContext (epoch-fenced collectives
    # over the virtual-shard grid); None under the legacy JaxTrainer
    gang: Optional[Any] = None
    # per-rank dataset shards (JaxTrainer datasets=), wrapped as
    # DataIterators at access time
    dataset_shards: Dict[str, Any] = field(default_factory=dict)
    # reporting channel back to the controller
    _reports: List[Dict[str, Any]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_world_size(self) -> int:
        return self.world_size

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def get_gang(self) -> Any:
        if self.gang is None:
            raise RuntimeError(
                "no gang context (not an elastic run; use ElasticTrainer)"
            )
        return self.gang

    def get_dataset_shard(self, name: str = "train") -> DataIterator:
        ds = self.dataset_shards.get(name)
        if ds is None:
            raise KeyError(
                f"no dataset shard {name!r}; pass datasets={{{name!r}: ds}} "
                "to JaxTrainer"
            )
        return DataIterator(ds)


_session = threading.local()


def _set_context(ctx: Optional[TrainContext]) -> None:
    _session.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_session, "ctx", None)
    if ctx is None:
        raise RuntimeError("not inside a train worker (no session context)")
    return ctx


def get_dataset_shard(name: str = "train") -> DataIterator:
    """ray.train.get_dataset_shard parity: this rank's shard of a
    dataset passed to JaxTrainer(datasets=...), as a streaming
    DataIterator."""
    return get_context().get_dataset_shard(name)


def report(
    metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
) -> None:
    """ray.train.report parity: record metrics (+ checkpoint) for this step."""
    import os

    ctx = get_context()
    with ctx._lock:
        ctx._reports.append(
            {"metrics": dict(metrics), "checkpoint": checkpoint}
        )
    if checkpoint is not None and ctx.trial_dir and ctx.world_rank == 0:
        # Durable pointer so the controller can restore after a crash even
        # when the checkpoint directory lives outside trial_dir.
        pointer = os.path.join(ctx.trial_dir, "_latest_checkpoint")
        tmp = pointer + ".tmp"
        with open(tmp, "w") as f:
            f.write(checkpoint.path)
        os.replace(tmp, pointer)
