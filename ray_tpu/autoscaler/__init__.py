"""ray_tpu.autoscaler — demand-driven cluster scaling on the binpack kernels."""
from .autoscaler import Autoscaler, NodeTypeConfig, SimNodeProvider  # noqa: F401
from .providers import (  # noqa: F401
    CloudAPIError,
    InstanceManager,
    LocalNodeProvider,
    MockCloudProvider,
)
