"""Autoscaler: pending demand → node launches/terminations.

V2-shaped (declarative reconcile, /root/reference/python/ray/autoscaler/v2/
scheduler.py:782,1016-1060) with the scoring/packing math running through the
batched kernels in ray_tpu.scheduler.binpack:

  tick():
    1. read pending demand from the runtime (queued + infeasible leases and
       unplaced PG bundles — GcsAutoscalerStateManager's ClusterResourceState)
    2. enforce min_workers per type
    3. residual = bin_pack_residual(current availability, demands)
    4. while residual nonempty and below max: pick node type via the
       utilization scorer (get_nodes_for semantics), add hypothetical node,
       recompute residual
    5. launch via the NodeProvider; terminate nodes idle past idle_timeout

The SimNodeProvider adds/removes nodes of the in-process runtime — the
fake_multi_node provider analog (autoscaler/_private/fake_multi_node/).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.scheduler.binpack import (
    DeltaBinPacker,
    bin_pack_residual,
    pick_best_node_type,
    sort_demands,
    utilization_scores,
)

NODE_TYPE_LABEL = "ray_tpu.io/node-type"


@dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class ScalingDecision:
    launch: Dict[str, int] = field(default_factory=dict)  # type -> count
    terminate: List[str] = field(default_factory=list)  # node ids


class SimNodeProvider:
    """Cloud provider stand-in: nodes materialize in the runtime."""

    def __init__(self, runtime):
        self.runtime = runtime

    def create_node(self, node_type: NodeTypeConfig) -> str:
        return self.runtime.add_node(
            dict(node_type.resources), labels={NODE_TYPE_LABEL: node_type.name}
        )

    def terminate_node(self, node_id: str) -> None:
        self.runtime.kill_node(node_id)

    def non_terminated_nodes(self) -> List[dict]:
        return [n for n in self.runtime.nodes_info() if n["Alive"]]


class Autoscaler:
    def __init__(
        self,
        runtime,
        node_types: List[NodeTypeConfig],
        *,
        provider: Optional[SimNodeProvider] = None,
        idle_timeout_s: float = 60.0,
        tick_interval_s: float = 1.0,
    ):
        self.runtime = runtime
        self.node_types = {t.name: t for t in node_types}
        self.provider = provider or SimNodeProvider(runtime)
        # cluster-mode runtimes have no resource vocab of their own
        if getattr(runtime, "vocab", None) is not None:
            self.vocab = runtime.vocab
        else:
            from ray_tpu.scheduler import ResourceVocab

            self.vocab = ResourceVocab()
        self.idle_timeout_s = idle_timeout_s
        self.tick_interval_s = tick_interval_s
        self._idle_since: Dict[str, float] = {}
        # device-resident residual packer: node rows stay on the scheduler
        # device across ticks; only changed rows are pushed per reconcile
        self._packer = DeltaBinPacker()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- control loop ---------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="ray_tpu-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - keep reconciling
                pass

    # -- one reconcile pass --------------------------------------------
    def tick(self) -> ScalingDecision:
        from ray_tpu.config import cfg

        if cfg.elastic_controller:
            # unified elasticity plane (PR 19): the head controller's
            # single solve owns provision/retire — a second loop sizing
            # the same fleet would race it (the exact thrash the
            # controller exists to end). No-op decision; flipping
            # RAY_TPU_ELASTIC_CONTROLLER=0 restores this loop untouched.
            return ScalingDecision()
        # v2 reconciler: retry lost launches, promote REQUESTED->RUNNING
        if hasattr(self.provider, "reconcile"):
            self.provider.reconcile()
        decision = self.plan()
        for type_name, count in decision.launch.items():
            for _ in range(count):
                self.provider.create_node(self.node_types[type_name])
        for node_id in decision.terminate:
            self.provider.terminate_node(node_id)
        return decision

    def plan(self) -> ScalingDecision:
        decision = ScalingDecision()
        nodes = self.provider.non_terminated_nodes()
        counts: Dict[str, int] = {t: 0 for t in self.node_types}
        for n in nodes:
            t = n["Labels"].get(NODE_TYPE_LABEL)
            if t in counts:
                counts[t] += 1
        # launches still in flight (REQUESTED at the instance manager)
        # count as existing capacity — otherwise every tick re-launches
        # the same demand until the first agents finish registering
        pending_launches: Dict[str, int] = {}
        if hasattr(self.provider, "pending_launches"):
            pending_launches = self.provider.pending_launches()
            for t, c in pending_launches.items():
                if t in counts:
                    counts[t] += c

        # 1. min_workers fill (_add_min_workers_nodes)
        for t in self.node_types.values():
            if counts[t.name] < t.min_workers:
                decision.launch[t.name] = t.min_workers - counts[t.name]
                counts[t.name] = t.min_workers

        # 2. demand-driven launches
        demands = self.runtime.pending_resource_demands()
        if demands:
            width = self.vocab.capacity
            dmat = np.stack(
                [self.vocab.pack(d).astype(np.float32) for d in demands]
            )[:, :width]
            dmat = dmat[sort_demands(dmat)]
            avail_keys = [n["NodeID"] for n in nodes]
            avail_rows = [
                self.vocab.pack(n["Available"])[:width] for n in nodes
            ]
            # nodes already queued for launch (min_workers fill) AND
            # launches in flight count as capacity — otherwise demand
            # double-provisions on cold start
            hypothetical = dict(decision.launch)
            for t, c in pending_launches.items():
                hypothetical[t] = hypothetical.get(t, 0) + c
            for type_name, count in hypothetical.items():
                if type_name not in self.node_types:
                    continue
                row = self.vocab.pack(
                    self.node_types[type_name].resources
                )[:width]
                avail_rows.extend([row] * count)
                avail_keys.extend(
                    f"hypothetical:{type_name}:{i}" for i in range(count)
                )
            if avail_rows:
                # delta-synced: node rows live on the scheduler device
                # across ticks, changed rows scatter-push; big demand
                # batches route through the projected-gradient solve,
                # small ones through the exact first-fit scan (binpack.py)
                packed = self._packer.pack_or_solve(
                    avail_keys, avail_rows, dmat
                )
                unfulfilled = dmat[packed < 0]
            else:
                # zero nodes (cold cluster): everything is unfulfilled —
                # the packing kernel needs at least one bin
                unfulfilled = dmat
            type_rows = {
                t.name: self.vocab.pack(t.resources)[:width]
                for t in self.node_types.values()
            }
            names = list(type_rows)
            guard = 0
            while len(unfulfilled) and guard < 64:
                guard += 1
                allowed = [
                    n
                    for n in names
                    if counts[n] + decision.launch.get(n, 0)
                    < self.node_types[n].max_workers
                ]
                if not allowed:
                    break
                types_mat = np.stack([type_rows[n] for n in allowed])
                scores = utilization_scores(types_mat, unfulfilled)
                best = pick_best_node_type(scores)
                if best < 0:
                    break
                chosen = allowed[best]
                decision.launch[chosen] = decision.launch.get(chosen, 0) + 1
                res = bin_pack_residual(
                    type_rows[chosen][None, :], unfulfilled
                )
                unfulfilled = unfulfilled[np.asarray(res.node) < 0]

        # 3. idle termination (keep min_workers)
        now = time.monotonic()
        local_nodes = getattr(self.runtime, "nodes", None)
        for n in nodes:
            nid = n["NodeID"]
            # Available==Resources alone is NOT idle: zero-resource actors
            # and tasks hold nothing — consult the Busy flag (cluster mode)
            # or running tasks + hosted alive actors (in-process mode)
            idle = n["Available"] == n["Resources"] and not n.get("Busy")
            if idle and local_nodes is not None and nid in local_nodes:
                idle = not local_nodes[nid].running_tasks
                if idle:
                    actors = getattr(self.runtime, "_actors", {})
                    idle = not any(
                        st.alive and st.node_id == nid
                        for st in actors.values()
                    )
            if idle:
                self._idle_since.setdefault(nid, now)
                t = n["Labels"].get(NODE_TYPE_LABEL)
                min_w = self.node_types[t].min_workers if t in self.node_types else 0
                if (
                    now - self._idle_since[nid] > self.idle_timeout_s
                    and t in counts
                    and counts[t] > min_w
                ):
                    decision.terminate.append(nid)
                    counts[t] -= 1
            else:
                self._idle_since.pop(nid, None)
        return decision
