"""Node providers + the v2 instance-manager reconciler.

Reference analogs: the provider zoo under
/root/reference/python/ray/autoscaler/ (aws/gcp/.../local/fake_multi_node
node_provider.py) and the v2 InstanceManager
(autoscaler/v2/instance_manager/) that reconciles desired instances
against what the cloud actually delivered.

``LocalNodeProvider`` is the real-process provider: create_node spawns an
actual ``ray_tpu.cluster.agent`` subprocess that registers with a live
head — the local/fake_multi_node pattern, except the nodes are fully
functional agents with worker pools and object stores. Cloud SDK
providers implement the same three methods against their APIs.

``InstanceManager`` wraps any provider with declarative instance records:
a launch is REQUESTED until the node appears in the head's membership,
RUNNING afterwards; launches that never materialize within the timeout
are retried (the v2 reconciler loop collapsed to its core).
"""
from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .autoscaler import NODE_TYPE_LABEL, NodeTypeConfig


class LocalNodeProvider:
    """Real agent subprocesses against a live head."""

    def __init__(self, head_address: str, num_workers: int = 2):
        self.head_address = head_address
        self.num_workers = num_workers
        self._procs: Dict[str, subprocess.Popen] = {}
        self._head = None
        self._lock = threading.Lock()

    def _head_client(self):
        if self._head is None:
            from ray_tpu.cluster.rpc import RpcClient

            self._head = RpcClient(self.head_address)
        return self._head

    def create_node(
        self, node_type: NodeTypeConfig, node_id: Optional[str] = None
    ) -> str:
        from ray_tpu.cluster.common import new_id

        node_id = node_id or new_id()
        resources = dict(node_type.resources)
        resources.setdefault("memory", float(4 << 30))
        resources.setdefault("object_store_memory", float(1 << 30))
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu.cluster.agent",
                "--head",
                self.head_address,
                "--resources",
                json.dumps(resources),
                "--labels",
                json.dumps({NODE_TYPE_LABEL: node_type.name}),
                "--num-workers",
                str(self.num_workers),
                "--node-id",
                node_id,
            ],
        )
        with self._lock:
            self._procs[node_id] = proc
        return node_id

    def terminate_node(self, node_id: str) -> None:
        # graceful: tell the agent to shut down (releases arena/ports);
        # the process handle is the backstop
        try:
            for n in self.non_terminated_nodes():
                if n["NodeID"] == node_id:
                    from ray_tpu.cluster.rpc import RpcClient

                    cli = RpcClient(n["Address"])
                    try:
                        cli.call("Shutdown", timeout=5.0)
                    finally:
                        cli.close()
                    break
        except Exception:  # noqa: BLE001 - hard kill below
            pass
        with self._lock:
            proc = self._procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                try:
                    proc.kill()
                    proc.wait(timeout=2)  # reap: no zombie accumulation
                except Exception:  # noqa: BLE001
                    pass

    def non_terminated_nodes(self) -> List[dict]:
        reply = self._head_client().call("ClusterInfo", timeout=15.0)
        return [n for n in reply["nodes"] if n["Alive"]]

    def shutdown(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            try:
                p.terminate()
                p.wait(timeout=2)
            except Exception:  # noqa: BLE001
                pass
        if self._head is not None:
            self._head.close()
            self._head = None


class CloudAPIError(RuntimeError):
    """Transient cloud-API rejection (rate limit, capacity)."""


class MockCloudProvider:
    """Flaky asynchronous cloud in front of LocalNodeProvider — the
    test double the reference shapes with batching_node_provider.py: a
    provider whose API is eventually-consistent and unreliable, driving
    REAL agent subprocesses underneath so the reconciler is proven
    against genuine registration/membership dynamics.

    - ``create_node`` returns a cloud-assigned node id immediately; the
      instance materializes later on a background thread after a random
      provisioning delay — or, with ``create_failure_rate`` probability,
      NEVER (request accepted, instance silently lost: the classic cloud
      failure the v2 reconciler's launch timeout + retry exists for).
    - ``terminate_node`` is also async (delayed on a background thread).
    - a token-bucket rate limit rejects API bursts with CloudAPIError.
    """

    def __init__(
        self,
        head_address: str,
        *,
        num_workers: int = 1,
        create_delay_s: tuple = (0.2, 1.5),
        create_failure_rate: float = 0.2,
        terminate_delay_s: float = 0.5,
        max_requests_per_s: float = 20.0,
        seed: int = 0,
    ):
        import random

        self._local = LocalNodeProvider(head_address, num_workers)
        self._rng = random.Random(seed)
        self._delay = create_delay_s
        self._fail = create_failure_rate
        self._term_delay = terminate_delay_s
        self._rate = max_requests_per_s
        self._tokens = max_requests_per_s
        self._t_last = time.monotonic()
        self._lock = threading.Lock()
        self.created = 0
        self.lost = 0

    def _take_token(self) -> None:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self._rate, self._tokens + (now - self._t_last) * self._rate
            )
            self._t_last = now
            if self._tokens < 1.0:
                raise CloudAPIError("RequestLimitExceeded")
            self._tokens -= 1.0

    def create_node(self, node_type: NodeTypeConfig) -> str:
        from ray_tpu.cluster.common import new_id

        self._take_token()
        node_id = new_id()  # cloud id exists before the instance does
        with self._lock:
            self.created += 1
            fail = self._rng.random() < self._fail
            delay = self._rng.uniform(*self._delay)
            if fail:
                self.lost += 1

        def materialize():
            time.sleep(delay)
            if fail:
                return  # silently lost launch
            try:
                self._local.create_node(node_type, node_id=node_id)
            except Exception:  # noqa: BLE001 - treat as lost
                pass

        threading.Thread(target=materialize, daemon=True).start()
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._take_token()

        def do_terminate():
            time.sleep(self._term_delay)
            try:
                self._local.terminate_node(node_id)
            except Exception:  # noqa: BLE001 - already gone
                pass

        threading.Thread(target=do_terminate, daemon=True).start()

    def non_terminated_nodes(self) -> List[dict]:
        return self._local.non_terminated_nodes()

    def shutdown(self) -> None:
        self._local.shutdown()


@dataclass
class _Instance:
    instance_id: str
    node_type: str
    state: str  # REQUESTED | RUNNING | TERMINATED
    node_id: Optional[str] = None
    requested_at: float = field(default_factory=time.monotonic)
    retries: int = 0


class InstanceManager:
    """Declarative reconcile over a provider (v2 instance_manager core):
    tracks every launch from REQUESTED to RUNNING, retries launches the
    provider lost, and exposes the same provider interface so the
    Autoscaler composes with it transparently."""

    def __init__(
        self,
        provider,
        *,
        launch_timeout_s: float = 60.0,
        max_retries: int = 2,
    ):
        self.provider = provider
        self.launch_timeout_s = launch_timeout_s
        self.max_retries = max_retries
        self.instances: Dict[str, _Instance] = {}
        self._counter = 0
        self._lock = threading.Lock()
        self._types: Dict[str, NodeTypeConfig] = {}

    # -- provider interface (delegated + recorded) ----------------------
    def create_node(self, node_type: NodeTypeConfig) -> str:
        node_id = self.provider.create_node(node_type)
        with self._lock:
            self._counter += 1
            iid = f"inst-{self._counter}"
            self._types[node_type.name] = node_type
            self.instances[iid] = _Instance(
                instance_id=iid,
                node_type=node_type.name,
                state="REQUESTED",
                node_id=node_id,
            )
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self.provider.terminate_node(node_id)
        with self._lock:
            for inst in self.instances.values():
                if inst.node_id == node_id:
                    inst.state = "TERMINATED"

    def non_terminated_nodes(self) -> List[dict]:
        return self.provider.non_terminated_nodes()

    # -- reconcile ------------------------------------------------------
    def reconcile(self) -> None:
        """REQUESTED instances whose node registered become RUNNING;
        launches that never materialized within the timeout are retried
        (up to max_retries) or marked TERMINATED."""
        alive = {n["NodeID"] for n in self.provider.non_terminated_nodes()}
        now = time.monotonic()
        relaunch: List[_Instance] = []
        reap: List[str] = []
        with self._lock:
            for inst in self.instances.values():
                if inst.state == "REQUESTED":
                    if inst.node_id in alive:
                        inst.state = "RUNNING"
                    elif now - inst.requested_at > self.launch_timeout_s:
                        inst.state = "TERMINATED"
                        if inst.retries < self.max_retries:
                            relaunch.append(inst)
                        elif inst.node_id is not None:
                            # retries exhausted: still reap the straggling
                            # process or it registers later as an untracked
                            # node (relaunch reaps its own below)
                            reap.append(inst.node_id)
                elif inst.state == "RUNNING" and inst.node_id not in alive:
                    # node died underneath us; record it (the autoscaler's
                    # demand loop decides whether replacement is needed)
                    inst.state = "TERMINATED"
        for node_id in reap:
            try:
                self.provider.terminate_node(node_id)
            except Exception:  # noqa: BLE001 - already gone
                pass
        for inst in relaunch:
            cfg = self._types.get(inst.node_type)
            if cfg is None:
                continue
            # reap the original launch FIRST: a slow-spawning agent that
            # registers after its replacement would over-provision the
            # cluster past max_workers
            if inst.node_id is not None:
                try:
                    self.provider.terminate_node(inst.node_id)
                except Exception:  # noqa: BLE001 - already gone
                    pass
            try:
                node_id = self.provider.create_node(cfg)
            except Exception:  # noqa: BLE001 - API rejection (rate limit)
                # cloud-API failure: record a REQUESTED launch with no
                # node so a later tick retries — but still burn a retry,
                # or a PERSISTENTLY failing API (bad credentials) would
                # relaunch forever and report phantom pending capacity
                node_id = None
            with self._lock:
                self._counter += 1
                iid = f"inst-{self._counter}"
                self.instances[iid] = _Instance(
                    instance_id=iid,
                    node_type=cfg.name,
                    state="REQUESTED",
                    node_id=node_id,
                    retries=inst.retries + 1,
                )

    def pending_launches(self) -> Dict[str, int]:
        """REQUESTED instances per node type — capacity the autoscaler
        must count as already on its way (or every tick re-launches the
        same demand until the first agents register)."""
        with self._lock:
            out: Dict[str, int] = {}
            for inst in self.instances.values():
                if inst.state == "REQUESTED":
                    out[inst.node_type] = out.get(inst.node_type, 0) + 1
            return out

    def summary(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for inst in self.instances.values():
                out[inst.state] = out.get(inst.state, 0) + 1
            return out

    def shutdown(self) -> None:
        if hasattr(self.provider, "shutdown"):
            self.provider.shutdown()
