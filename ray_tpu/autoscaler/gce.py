"""GCE TPU-VM node provider (cloud-API-backed, transport-injected).

Capability analog of the reference's cloud providers + batching
abstraction (/root/reference/python/ray/autoscaler/_private/gcp/ and
batching_node_provider.py): the autoscaler's InstanceManager drives a
REAL cloud API — here the TPU VM REST surface
(tpu.googleapis.com/v2/projects/{p}/locations/{z}/nodes) — instead of
local subprocesses.

Design for testability-without-cloud (this image has zero egress): every
HTTP call goes through an injected ``transport(method, url, body) ->
(status, json)``. The default transport authenticates via the GCE
metadata server and uses urllib — usable on a real TPU-VM head node —
while tests inject a fake that proves the request shapes, async
operation handling, rate-limit mapping, and reconciler integration.

TPU-pod mapping: an accelerator type like ``v5e-16`` provisions one
SLICE; the provider labels the node with its slice name so the
scheduler's ICI-domain locality (PG STRICT_PACK ≙ same slice — the
reference approximates this via util/tpu.py:226-265) sees cloud slices
as first-class locality groups.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .autoscaler import NodeTypeConfig
from .providers import CloudAPIError

SLICE_LABEL = "ray_tpu.io/slice"


def metadata_token_transport(timeout_s: float = 10.0) -> Callable:
    """Default transport: OAuth token from the GCE metadata server +
    urllib. Only works ON a GCP VM with a TPU-scoped service account."""
    import urllib.request

    def _token() -> str:
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"},
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read())["access_token"]

    def transport(method: str, url: str, body: Optional[dict]) -> Tuple[int, dict]:
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={
                "Authorization": f"Bearer {_token()}",
                "Content-Type": "application/json",
            },
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as r:
                payload = r.read()
                return r.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:  # structured cloud errors
            try:
                return e.code, json.loads(e.read())
            except Exception:  # noqa: BLE001
                return e.code, {}

    return transport


class GceTpuNodeProvider:
    """TPU-VM nodes via the Cloud TPU REST API.

    ``create_node`` issues the create and returns the cloud node id
    immediately (reference NodeProvider contract: creation is async and
    eventually consistent); a background thread polls the returned
    long-running operation. ``non_terminated_nodes`` lists live nodes —
    the InstanceManager's reconciler (providers.py) resolves requested-
    but-never-materialized launches against it exactly as with the mock
    provider, which is the point of sharing that machinery."""

    API = "https://tpu.googleapis.com/v2"

    def __init__(
        self,
        project: str,
        zone: str,
        *,
        runtime_version: str = "tpu-ubuntu2204-base",
        head_address: str = "",
        startup_script: Optional[str] = None,
        transport: Optional[Callable] = None,
        poll_interval_s: float = 5.0,
        network: Optional[str] = None,
    ):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.head_address = head_address
        self.startup_script = startup_script
        self.poll_interval_s = poll_interval_s
        self.network = network
        self._transport = transport or metadata_token_transport()
        self._lock = threading.Lock()
        self._ops: Dict[str, str] = {}  # node_id -> operation name
        self._shutdown = False

    # ------------------------------------------------------------------
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _call(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        status, payload = self._transport(
            method, f"{self.API}/{path}", body
        )
        if status == 429 or status == 403 and "rate" in str(payload).lower():
            raise CloudAPIError(f"rate limited: {payload}")
        if status >= 400:
            raise CloudAPIError(f"TPU API {method} {path} -> {status}: {payload}")
        return payload

    @staticmethod
    def _accelerator_of(node_type: NodeTypeConfig) -> str:
        """The slice shape: an explicit ``accelerator_type`` label-style
        key in resources metadata is not expressible, so the convention
        is TPU count -> v5e slice ("TPU": 8 -> "v5litepod-8")."""
        chips = int(node_type.resources.get("TPU", 0) or 0)
        if chips <= 0:
            raise ValueError(
                f"node type {node_type.name!r} has no TPU resource; "
                "GceTpuNodeProvider provisions TPU-VM slices"
            )
        return f"v5litepod-{chips}"

    def create_node(self, node_type: NodeTypeConfig) -> str:
        from ray_tpu.cluster.common import new_id

        node_id = f"tpu-{node_type.name}-{new_id()[:8]}"
        body = {
            "acceleratorType": self._accelerator_of(node_type),
            "runtimeVersion": self.runtime_version,
            "labels": {
                "ray-tpu-node-type": node_type.name,
                SLICE_LABEL.replace("/", "-").replace(".", "-"): node_id,
            },
            "metadata": {
                "ray-tpu-head-address": self.head_address,
                **(
                    {"startup-script": self.startup_script}
                    if self.startup_script
                    else {}
                ),
            },
        }
        if self.network:
            body["networkConfig"] = {"network": self.network}
        op = self._call(
            "POST", f"{self._parent()}/nodes?nodeId={node_id}", body
        )
        with self._lock:
            self._ops[node_id] = op.get("name", "")
        threading.Thread(
            target=self._poll_operation,
            args=(node_id, op.get("name", "")),
            daemon=True,
            name=f"gce-op-{node_id[:12]}",
        ).start()
        return node_id

    def _poll_operation(self, node_id: str, op_name: str) -> None:
        """Long-running-operation poll: done+error → the launch is lost
        (the reconciler's launch timeout re-requests it); done+ok → the
        VM's startup script joins the head on its own."""
        while op_name and not self._shutdown:
            time.sleep(self.poll_interval_s)
            try:
                op = self._call("GET", op_name)
            except CloudAPIError:
                continue  # transient; keep polling
            if op.get("done"):
                with self._lock:
                    self._ops.pop(node_id, None)
                return

    def terminate_node(self, node_id: str) -> None:
        self._call("DELETE", f"{self._parent()}/nodes/{node_id}")

    def non_terminated_nodes(self) -> List[dict]:
        payload = self._call("GET", f"{self._parent()}/nodes")
        out = []
        for node in payload.get("nodes", ()):
            state = node.get("state", "")
            if state in ("DELETING", "TERMINATED", "PREEMPTED"):
                continue
            name = node.get("name", "").rsplit("/", 1)[-1]
            out.append(
                {
                    # "NodeID" matches the other providers' row shape —
                    # the InstanceManager reconciler keys on it
                    "NodeID": name,
                    "Alive": True,
                    "type": node.get("labels", {}).get(
                        "ray-tpu-node-type", ""
                    ),
                    "state": state,
                    "slice": name,
                }
            )
        return out

    def shutdown(self) -> None:
        self._shutdown = True
