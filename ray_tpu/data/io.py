"""Dataset IO: parquet / CSV / JSON / numpy / pandas interchange.

The slim analog of the reference's datasource layer
(/root/reference/python/ray/data/read_api.py + _internal/datasource/):
file discovery on the driver, one read task per file (parallel via the
task layer). Readers produce **Arrow-table blocks** (block.py — the
reference's arrow_block.py format) so downstream ``map_batches`` /
``iter_batches`` get zero-copy views; row-oriented consumers see rows
through the block accessors.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import ray_tpu

from .dataset import Dataset, from_items


def _discover(paths, suffixes: tuple) -> List[str]:
    """Regular files with a matching extension only — foreign entries
    (_SUCCESS markers, subdirs, mixed formats) must not fail the read."""
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                fp
                for f in sorted(os.listdir(p))
                if not f.startswith(".")
                and f.lower().endswith(suffixes)
                and os.path.isfile(fp := os.path.join(p, f))
            )
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no {suffixes} files under {paths}")
    return files


@ray_tpu.remote
def _read_parquet_file(path: str, columns):
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)  # Arrow block


@ray_tpu.remote
def _read_csv_file(path: str):
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path)  # Arrow block


@ray_tpu.remote
def _read_json_file(path: str):
    """JSON-lines or a top-level JSON array of objects → Arrow block."""
    import json

    import pyarrow as pa

    with open(path, "r") as f:
        text = f.read()
    if text.lstrip().startswith("["):
        rows = json.loads(text)
    else:
        rows = [
            json.loads(line) for line in text.splitlines() if line.strip()
        ]
    return pa.Table.from_pylist(rows)


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    refs = [
        _read_parquet_file.remote(p, columns)
        for p in _discover(paths, (".parquet", ".pq"))
    ]
    return Dataset(refs, [])


def read_csv(paths) -> Dataset:
    refs = [_read_csv_file.remote(p) for p in _discover(paths, (".csv",))]
    return Dataset(refs, [])


def read_json(paths) -> Dataset:
    """read_json parity (reference read_api.py read_json): .json /
    .jsonl files, JSON-lines or array-of-objects."""
    refs = [
        _read_json_file.remote(p)
        for p in _discover(paths, (".json", ".jsonl"))
    ]
    return Dataset(refs, [])


def from_numpy(arr, *, column: str = "data", num_blocks: int = 1) -> Dataset:
    """Dataset over a numpy array (reference from_numpy): Arrow-table
    blocks whose column references the array's buffer zero-copy. 1-D
    arrays become scalar rows, 2-D arrays one fixed-size-list row per
    outer index; higher ranks are rejected loudly (a flattened
    FixedSizeList would silently change the row count)."""
    import pyarrow as pa

    arr = np.asarray(arr)
    if arr.ndim > 2:
        raise ValueError(
            f"from_numpy supports 1-D and 2-D arrays; got shape {arr.shape}"
            " — reshape to (rows, features) first"
        )
    blocks = []
    for chunk in np.array_split(arr, max(1, num_blocks)):
        if chunk.ndim <= 1:
            col = pa.array(chunk)
        else:
            col = pa.FixedSizeListArray.from_arrays(
                pa.array(chunk.reshape(-1)), chunk.shape[-1]
            )
        blocks.append(pa.table({column: col}))
    return Dataset(blocks, [])


def write_parquet(ds: Dataset, path: str) -> List[str]:
    """One file per block (the reference writes one file per block task)."""
    import pyarrow.parquet as pq

    from . import block as blk

    os.makedirs(path, exist_ok=True)
    out = []
    for i, block in enumerate(ds.iter_blocks()):
        if blk.block_len(block) == 0:
            continue
        file_path = os.path.join(path, f"part-{i:05d}.parquet")
        pq.write_table(blk.block_to_table(block), file_path)
        out.append(file_path)
    return out


def write_csv(ds: Dataset, path: str) -> List[str]:
    import pyarrow.csv as pacsv

    from . import block as blk

    os.makedirs(path, exist_ok=True)
    out = []
    for i, block in enumerate(ds.iter_blocks()):
        if blk.block_len(block) == 0:
            continue
        file_path = os.path.join(path, f"part-{i:05d}.csv")
        pacsv.write_csv(blk.block_to_table(block), file_path)
        out.append(file_path)
    return out


def from_pandas(df) -> Dataset:
    """Arrow-table block over the DataFrame (zero-copy for numeric
    columns via pyarrow's pandas bridge)."""
    import pyarrow as pa

    return Dataset([pa.Table.from_pandas(df, preserve_index=False)], [])


def to_pandas(ds: Dataset):
    import pandas as pd

    rows = [
        r if isinstance(r, dict) else {"data": r} for r in ds.iter_rows()
    ]
    return pd.DataFrame(rows)
