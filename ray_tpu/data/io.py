"""Dataset IO: parquet / CSV / pandas interchange.

The slim analog of the reference's datasource layer
(/root/reference/python/ray/data/read_api.py + _internal/datasource/):
file discovery on the driver, one read task per file (parallel via the
task layer), arrow-backed parquet and csv.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import ray_tpu

from .dataset import Dataset, from_items


def _discover(paths, suffixes: tuple) -> List[str]:
    """Regular files with a matching extension only — foreign entries
    (_SUCCESS markers, subdirs, mixed formats) must not fail the read."""
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                fp
                for f in sorted(os.listdir(p))
                if not f.startswith(".")
                and f.lower().endswith(suffixes)
                and os.path.isfile(fp := os.path.join(p, f))
            )
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no {suffixes} files under {paths}")
    return files


@ray_tpu.remote
def _read_parquet_file(path: str, columns) -> list:
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns)
    return table.to_pylist()


@ray_tpu.remote
def _read_csv_file(path: str) -> list:
    import pyarrow.csv as pacsv

    return pacsv.read_csv(path).to_pylist()


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    refs = [
        _read_parquet_file.remote(p, columns)
        for p in _discover(paths, (".parquet", ".pq"))
    ]
    return Dataset(refs, [])


def read_csv(paths) -> Dataset:
    refs = [_read_csv_file.remote(p) for p in _discover(paths, (".csv",))]
    return Dataset(refs, [])


def write_parquet(ds: Dataset, path: str) -> List[str]:
    """One file per block (the reference writes one file per block task)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    out = []
    for i, block in enumerate(ds.iter_blocks()):
        if not block:
            continue
        rows = [r if isinstance(r, dict) else {"data": r} for r in block]
        file_path = os.path.join(path, f"part-{i:05d}.parquet")
        pq.write_table(pa.Table.from_pylist(rows), file_path)
        out.append(file_path)
    return out


def write_csv(ds: Dataset, path: str) -> List[str]:
    import pyarrow as pa
    import pyarrow.csv as pacsv

    os.makedirs(path, exist_ok=True)
    out = []
    for i, block in enumerate(ds.iter_blocks()):
        if not block:
            continue
        rows = [r if isinstance(r, dict) else {"data": r} for r in block]
        file_path = os.path.join(path, f"part-{i:05d}.csv")
        pacsv.write_csv(pa.Table.from_pylist(rows), file_path)
        out.append(file_path)
    return out


def from_pandas(df) -> Dataset:
    return from_items(df.to_dict("records"))


def to_pandas(ds: Dataset):
    import pandas as pd

    rows = [
        r if isinstance(r, dict) else {"data": r} for r in ds.iter_rows()
    ]
    return pd.DataFrame(rows)
