"""Block representations: Python row-lists and Arrow tables.

Capability analog of the reference's Arrow block format
(/root/reference/python/ray/data/_internal/arrow_block.py): a Dataset
block is either a plain Python list of rows (``from_items``/``range``
data) or a ``pyarrow.Table`` (everything tabular: the file readers,
``from_numpy``/``from_pandas``). Table blocks give the batch paths
zero-copy views — ``batch_format="pyarrow"`` slices the table,
``batch_format="numpy"`` wraps column buffers without copying where
Arrow allows (numeric, no nulls) — while row-oriented ops
(map/filter/iter_rows, hash partitioning) materialize rows once at the
op boundary, mirroring the reference's block-accessor row views.
"""
from __future__ import annotations

from typing import Any, Iterator, List


def is_arrow(block: Any) -> bool:
    # cheap structural check: avoid importing pyarrow for list blocks
    return type(block).__module__.startswith("pyarrow")


def block_len(block: Any) -> int:
    return block.num_rows if is_arrow(block) else len(block)


_SYNTH_KEY = b"ray_tpu_synthetic_column"


def _is_synthetic(table: Any) -> bool:
    """True only for tables WE built around scalar rows (schema-metadata
    marker) — matching on a user-visible column name would corrupt real
    datasets whose only column happens to be called "data"."""
    meta = table.schema.metadata
    return bool(meta) and _SYNTH_KEY in meta


def block_rows(block: Any) -> List[Any]:
    """Row-list view (materializes a Table; unwraps the marker-tagged
    synthetic scalar column so scalar datasets round-trip)."""
    if not is_arrow(block):
        return block
    if _is_synthetic(block):
        name = block.schema.metadata[_SYNTH_KEY].decode()
        return block.column(name).to_pylist()
    return block.to_pylist()


def rows_iter(block: Any) -> Iterator[Any]:
    if is_arrow(block):
        yield from block_rows(block)
    else:
        yield from block


def is_ndarray(block: Any) -> bool:
    """ndarray blocks: rows along axis 0 (shuffle map/reduce outputs of
    numeric datasets, ``from_numpy``). They ride the object plane as
    buffer-backed pickle-5 frames — arena scatter writes on seal,
    zero-copy views on same-node reads."""
    import numpy as np

    return isinstance(block, np.ndarray)


def block_nbytes(block: Any) -> int:
    """Byte size for block-size-aware repartitioning."""
    if is_arrow(block):
        return int(block.nbytes)
    if is_ndarray(block):
        return int(block.nbytes)
    import cloudpickle

    try:
        return len(cloudpickle.dumps(block))
    except Exception:  # noqa: BLE001
        return 64 << 10


def arrow_to_batch(table: Any, batch_format: str):
    """A batch view of a Table slice. "pyarrow": the slice itself
    (zero-copy). "numpy"/"default": dict of numpy arrays over the column
    buffers — zero-copy where Arrow permits. "pandas": DataFrame."""
    if batch_format == "pyarrow":
        return table
    if batch_format == "pandas":
        return table.to_pandas()
    out = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            if col.num_chunks == 1:
                # single chunk: a true buffer view (combine_chunks would
                # consolidate into a fresh allocation even for one chunk)
                out[name] = col.chunk(0).to_numpy(zero_copy_only=True)
            else:
                out[name] = col.combine_chunks().to_numpy(
                    zero_copy_only=True
                )
        except Exception:  # noqa: BLE001 - nulls/strings: copy is required
            out[name] = col.to_numpy(zero_copy_only=False)
    return out


def batch_to_block(result: Any):
    """A map_batches result back to a block, preferring Arrow for
    tabular shapes (Table stays Table; DataFrame and dict-of-arrays
    become Tables) so downstream batch stages keep zero-copy views."""
    if is_arrow(result):
        return result
    import pyarrow as pa

    if type(result).__name__ == "DataFrame":
        # preserve_index=False: a filtered frame's non-trivial index must
        # not become a spurious __index_level_0__ column
        return pa.Table.from_pandas(result, preserve_index=False)
    if isinstance(result, dict):
        return pa.table(result)
    return list(result)  # row list


def rows_to_arrow(rows: List[Any]):
    import pyarrow as pa

    if rows and isinstance(rows[0], dict):
        return pa.Table.from_pylist(rows)
    return synthetic_table(pa.array(list(rows)), "data")


def synthetic_table(arr: Any, column: str):
    """A single-column table tagged as wrapping scalar rows (see
    _is_synthetic)."""
    import pyarrow as pa

    return pa.table({column: arr}).replace_schema_metadata(
        {_SYNTH_KEY: column.encode()}
    )


def block_to_table(block: Any):
    """A writable Table from any block (shared by the parquet/csv
    writers): Arrow blocks pass through; scalar rows wrap in a "data"
    column like the reference's tensor/scalar handling."""
    if is_arrow(block):
        return block
    import pyarrow as pa

    rows = [r if isinstance(r, dict) else {"data": r} for r in block]
    return pa.Table.from_pylist(rows)


def concat_blocks(blocks: List[Any]):
    """One block from many (repartition coalescing): all-Arrow inputs
    concat zero-copy; all-ndarray inputs concat into one buffer;
    otherwise rows."""
    if blocks and all(is_arrow(b) for b in blocks):
        import pyarrow as pa

        return pa.concat_tables(blocks)
    if blocks and all(is_ndarray(b) for b in blocks):
        import numpy as np

        try:
            return np.concatenate(blocks)
        except ValueError:  # mismatched shapes/dtypes: fall through
            pass
    out: List[Any] = []
    for b in blocks:
        out.extend(block_rows(b))
    return out


def slice_block(block: Any, start: int, length: int):
    if is_arrow(block):
        return block.slice(start, length)  # zero-copy
    return block[start:start + length]
