"""Distributed shuffle primitives: hash/range partition + reduce.

The analog of the reference's hash-shuffle operator family
(/root/reference/python/ray/data/_internal/execution/operators/
hash_shuffle.py and planner/exchange/): a map stage partitions every
block by key (hash or sampled range), a reduce stage gathers one
partition id from all map outputs — all as framework tasks over the
object plane, so shuffles ride the same lease/object machinery as any
other workload.

Streaming-shuffle plane (ISSUE 13 / ROADMAP 5):

- **Map side** is vectorized for numeric blocks: destinations come from
  one hashed/bincounted pass and partitions are gathered with a stable
  argsort (``_gather_parts``) instead of per-row list appends; ndarray
  blocks keep their partitions as buffer-backed arrays, so each
  partition's pickle-5 frames scatter-write straight into the local shm
  arena at seal time (worker ``put_value`` → ``put_frames``) — map
  outputs are sealed arena objects from birth, never driver round-trips.
  The row loop remains the generic fallback (non-numeric keys,
  ``RAY_TPU_DATA_VECTOR_SHUFFLE=0``).
- **Placement**: reduce tasks carry ObjectRef deps, which routes them
  through the head kernel; with ``cfg.sched_w_locality`` > 0 the round
  prep uploads per-(shape, node) resident-bytes and the kernel's
  locality term lands each reduce where its map partitions live
  (cluster/head.py ``_round_shapes``, scheduler/hybrid.py
  ``_shape_cost``).
- **Reduce side**: non-resident partitions fetch over the peer-leased
  socket plane with the cross-fetch in-flight byte gate as arena
  backpressure (cluster/transport.py); consumed map-partition refs are
  freed eagerly per reduce seal (``_EagerFreeWatcher``) so a shuffle is
  out-of-core — arena fill is bounded by in-flight reduces, not dataset
  size.
"""
from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

import ray_tpu
from ray_tpu.config import cfg
from ray_tpu.util.metrics import Counter as _Counter

SHUFFLE_PARTS_FREED = _Counter(
    "shuffle_partitions_freed_total",
    "Map-partition refs freed eagerly as their reduce task sealed.",
)


def _stable_hash(value: Any) -> int:
    """Deterministic across worker processes (builtin hash() is salted) and
    type-insensitive for numerics: 1, 1.0, and np.float64(1.0) must land in
    the same partition or groupby/join silently split equal keys."""
    if isinstance(value, (bool, np.bool_)):
        # np.bool_ is NOT a bool subclass: without this it fell through
        # to the repr digest, so True and np.True_ did not co-partition
        value = int(value)
    if isinstance(value, (int, np.integer)):
        return int(value)
    if isinstance(value, (float, np.floating)):
        f = float(value)
        if f.is_integer():
            return int(f)
        data = repr(f).encode()
    elif isinstance(value, (str, np.str_)):
        data = str(value).encode()
    else:
        data = repr(value).encode()
    return int.from_bytes(hashlib.md5(data).digest()[:8], "little")


def _hash_dests(keys: np.ndarray, num_parts: int) -> Optional[np.ndarray]:
    """Vectorized ``_stable_hash(key) % num_parts`` for numeric key
    arrays, or None when the dtype needs the scalar path. Must agree
    with the scalar digest EXACTLY across dtypes (1, 1.0 and
    np.float64(1.0) co-partition): integers hash to themselves, integral
    floats to int(f), and only the non-integral minority takes the
    per-element md5 fallback."""
    if keys.ndim != 1:
        return None
    if keys.dtype == bool:
        keys = keys.astype(np.int64)
    if np.issubdtype(keys.dtype, np.unsignedinteger):
        if keys.size and int(keys.max()) > np.iinfo(np.int64).max:
            return None  # int64 cast would wrap; scalar path is exact
        keys = keys.astype(np.int64)
    if np.issubdtype(keys.dtype, np.integer):
        # int64 % positive is a floor mod, matching Python's
        return (keys.astype(np.int64, copy=False) % num_parts).astype(
            np.int64
        )
    if not np.issubdtype(keys.dtype, np.floating):
        return None
    f = keys.astype(np.float64, copy=False)
    dest = np.empty(f.shape[0], dtype=np.int64)
    integral = np.isfinite(f) & (np.floor(f) == f) & (np.abs(f) < 2.0**63)
    dest[integral] = f[integral].astype(np.int64) % num_parts
    for i in np.flatnonzero(~integral):
        dest[i] = _stable_hash(float(f[i])) % num_parts
    return dest


def _vector_dests(
    rows: Any,
    num_parts: int,
    mode: str,
    key_list: Optional[List[Any]],
    bounds: Optional[List[Any]],
    seed: Optional[int],
) -> Optional[np.ndarray]:
    """int64[n] partition destination per row, or None when this block
    needs the generic row loop. Must compute destinations IDENTICAL to
    the row loop's — both paths coexist across workers in one shuffle.
    ``key_list``: pre-extracted per-row keys when a key_fn exists —
    extracted ONCE by the caller so a vectorization bail-out doesn't pay
    the key_fn pass twice."""
    n = len(rows)
    if mode == "random":
        rng = np.random.default_rng(seed)
        return rng.integers(0, num_parts, size=n).astype(np.int64)
    if key_list is not None:
        try:
            keys = np.asarray(key_list)
        except (TypeError, ValueError):  # ragged / exotic keys
            return None
    elif isinstance(rows, np.ndarray):
        keys = rows
    else:
        try:
            keys = np.asarray(rows)
        except (TypeError, ValueError):
            return None
    if keys.ndim != 1 or keys.dtype.kind not in "biuf":
        return None
    if mode == "hash":
        return _hash_dests(keys, num_parts)
    if mode == "range":
        try:
            barr = np.asarray(bounds)
        except (TypeError, ValueError):
            return None
        if barr.ndim != 1 or barr.dtype.kind not in "biuf":
            return None
        # first bound > key == bisect_right, the row loop's binary search
        dest = np.searchsorted(barr, keys, side="right").astype(np.int64)
        if keys.dtype.kind == "f":
            # NaN: every `bound <= key` comparison in the row loop is
            # False, so it lands in partition 0 — searchsorted would
            # send it to the LAST partition (NaN sorts greatest), and
            # the two paths must agree block-to-block
            dest[np.isnan(keys)] = 0
        return dest
    return None


def _gather_parts(
    rows: Any, dest: np.ndarray, num_parts: int
) -> List[Any]:
    """Partition lists from a destination vector. ndarray blocks gather
    with one stable argsort and stay ndarray partitions (contiguous
    slices → buffer-backed pickle-5 frames → arena scatter writes);
    other blocks keep list partitions with the vectorized destinations
    (row order within a partition matches the append loop's)."""
    if isinstance(rows, np.ndarray):
        order = np.argsort(dest, kind="stable")
        counts = np.bincount(dest, minlength=num_parts)
        ends = np.cumsum(counts)
        g = rows[order]
        return [
            g[e - c : e] for c, e in zip(counts.tolist(), ends.tolist())
        ]
    parts: List[List[Any]] = [[] for _ in range(num_parts)]
    for row, d in zip(rows, dest):
        parts[d].append(row)
    return parts


def _compute_parts(
    block: Any,
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
) -> List[Any]:
    """Split one block into num_parts row containers (shared by both map
    tasks): vectorized destinations + gather when the block/keys are
    numeric, the generic row loop otherwise."""
    from .block import block_rows

    block = block_rows(block)  # hash/range partitioning is row-wise
    if mode not in ("random", "hash", "range"):
        raise ValueError(f"unknown partition mode {mode}")
    # keys extracted ONCE: both the vectorized digest and the row-loop
    # fallback consume this list, so a vectorization bail-out never runs
    # the key_fn over the block a second time
    key_list: Optional[List[Any]] = (
        [key_fn(r) for r in block]
        if key_fn is not None and mode in ("hash", "range")
        else None
    )
    if len(block) and num_parts > 0 and cfg.data_vector_shuffle:
        dest = _vector_dests(
            block, num_parts, mode, key_list, bounds, seed
        )
        if dest is not None:
            return _gather_parts(block, dest, num_parts)
    parts: List[List[Any]] = [[] for _ in range(num_parts)]
    if mode == "random":
        rng = np.random.default_rng(seed)
        dest = rng.integers(0, num_parts, size=len(block))
        for row, d in zip(block, dest):
            parts[int(d)].append(row)
    elif mode == "hash":
        keys = key_list if key_list is not None else block
        for row, key in zip(block, keys):
            parts[_stable_hash(key) % num_parts].append(row)
    elif mode == "range":
        keys = key_list if key_list is not None else block
        for row, key in zip(block, keys):
            lo, hi = 0, len(bounds)  # first bound > key
            while lo < hi:
                mid = (lo + hi) // 2
                if bounds[mid] <= key:
                    lo = mid + 1
                else:
                    hi = mid
            parts[lo].append(row)
    return parts


@ray_tpu.remote
def _partition_block(
    block: List[Any],
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
) -> tuple:
    """Map side (N-return form): split one block into num_parts lists."""
    parts = _compute_parts(block, num_parts, mode, key_fn, bounds, seed)
    if num_parts == 1:
        return parts[0]  # num_returns=1 -> single (unwrapped) return value
    return tuple(parts)


@ray_tpu.remote
def _partition_block_stream(
    block: List[Any],
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
):
    """Map side (streaming form): yield partitions in index order.

    Each partition seals as its own object the moment it is yielded
    (num_returns="streaming"), so reduce p launches as soon as every map
    has emitted its p-th partition — the reference's streaming block
    emission for shuffles (hash_shuffle.py rides ObjectRefGenerator the
    same way) instead of waiting for whole map tasks."""
    for part in _compute_parts(block, num_parts, mode, key_fn, bounds, seed):
        yield part


def _all_ndarray(parts: Sequence[Any]) -> bool:
    return bool(parts) and all(
        isinstance(p, np.ndarray) and p.ndim >= 1 for p in parts
    )


@ray_tpu.remote
def _reduce_concat(*parts: List[Any]) -> List[Any]:
    if _all_ndarray(parts):
        # ndarray partitions concat into an ndarray block: the reduce
        # output stays a single buffer → one arena entry, zero-copy
        # batch slicing downstream
        return np.concatenate(parts)
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    return out


@ray_tpu.remote
def _reduce_shuffled(seed: int, *parts: List[Any]) -> List[Any]:
    rng = np.random.default_rng(seed)
    if _all_ndarray(parts):
        merged = np.concatenate(parts)
        return merged[rng.permutation(len(merged))]
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    return [out[i] for i in rng.permutation(len(out))]


@ray_tpu.remote
def _reduce_sorted(key_fn: Optional[Callable], descending: bool, *parts) -> List[Any]:
    if (
        key_fn is None
        and _all_ndarray(parts)
        and all(p.ndim == 1 for p in parts)
    ):
        # 1-D only: np.sort's axis=-1 would reorder WITHIN rows of a
        # multi-dim partition (silent corruption), not order the rows
        merged = np.sort(np.concatenate(parts), kind="stable")
        return merged[::-1].copy() if descending else merged
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    out.sort(key=key_fn, reverse=descending)
    return out


class _EagerFreeWatcher(threading.Thread):
    """Frees each map-partition ref the moment its LAST consuming reduce
    SEALS (success or exhausted-retries error), in _flush_frees-style
    batches — the shuffle analog of the streaming executor's eager
    intermediate frees. Tracking is per INPUT ref, not per reduce: the
    streaming form can hand one ref to several reduces (a map that
    errors mid-stream repeats its sealed-error ref for every remaining
    partition), and freeing it at the first consumer's seal would strand
    the rest on an unresolvable dep. Bounds arena fill by in-flight
    reduces instead of the whole map stage; the trade (documented on
    cfg.data_shuffle_eager_free) is that an already-sealed reduce output
    can no longer re-reconstruct from freed inputs. Partitions of
    reduces still PENDING are untouched, so mid-shuffle lineage
    reconstruction (node death) keeps working on exactly the lost
    partitions."""

    _BATCH = 64

    def __init__(self, rt, pairs: List[Tuple[Any, List[Any]]]):
        super().__init__(name="shuffle-eager-free", daemon=True)
        self._rt = rt
        self._pairs = pairs

    def run(self) -> None:
        reduces: dict = {}  # reduce hex -> (reduce ref, [input hexes])
        inputs: dict = {}   # input hex -> [input ref, remaining consumers]
        for r, ins in self._pairs:
            reduces[r.hex] = (r, [i.hex for i in ins])
            for i in ins:
                ent = inputs.get(i.hex)
                if ent is None:
                    inputs[i.hex] = [i, 1]
                else:
                    ent[1] += 1
        batch: List[Any] = []
        try:
            while reduces:
                # fate-share with the runtime that owns these refs: a
                # shut-down or replaced runtime makes the frees moot, and
                # a watcher polling wait() against a LATER runtime would
                # spin (and sleep) forever on refs it never knew
                from ray_tpu.core.runtime import get_runtime

                try:
                    cur = get_runtime()
                except Exception:  # noqa: BLE001 - no runtime: exit
                    return
                if (
                    cur is not self._rt
                    or getattr(self._rt, "_shutdown", False)
                    or getattr(self._rt, "_shutdown_done", False)
                ):
                    return
                refs = [v[0] for v in reduces.values()]
                ready, _ = ray_tpu.wait(
                    refs, num_returns=len(refs), timeout=2.0
                )
                for r in ready:
                    _, in_hexes = reduces.pop(r.hex, (None, []))
                    for h in in_hexes:
                        ent = inputs.get(h)
                        if ent is None:
                            continue
                        ent[1] -= 1
                        if ent[1] <= 0:
                            batch.append(ent[0])
                            del inputs[h]
                if batch and (len(batch) >= self._BATCH or not reduces):
                    self._free(batch)
                    batch = []
        except Exception:  # noqa: BLE001 - eager GC is advisory
            pass

    def _free(self, refs: List[Any]) -> None:
        free = getattr(self._rt, "free_objects", None)
        if free is None:
            return
        try:
            free(refs)
            SHUFFLE_PARTS_FREED.inc(len(refs))
        except Exception:  # noqa: BLE001
            pass


def _watch_eager_free(pairs: List[Tuple[Any, List[Any]]]) -> None:
    """Start the per-shuffle eager-free watcher when the runtime supports
    hard frees (no-op on the in-process local runtime) and the knob is
    on."""
    if not cfg.data_shuffle_eager_free or not pairs:
        return
    pairs = [
        (r, [i for i in ins if isinstance(i, ray_tpu.ObjectRef)])
        for r, ins in pairs
        if isinstance(r, ray_tpu.ObjectRef)
    ]
    pairs = [(r, ins) for r, ins in pairs if ins]
    if not pairs:
        return
    try:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
    except Exception:  # noqa: BLE001
        return
    if getattr(rt, "free_objects", None) is None:
        return
    _EagerFreeWatcher(rt, pairs).start()


def shuffle_blocks(
    blocks: List[List[Any]],
    num_parts: int,
    *,
    mode: str = "hash",
    key_fn: Optional[Callable] = None,
    bounds: Optional[List[Any]] = None,
    seed: Optional[int] = None,
    reduce_fn=None,
    reduce_args: tuple = (),
    streaming: Optional[bool] = None,
) -> List[Any]:
    """Run the two-stage shuffle; returns one ObjectRef per output part.

    Default (``streaming=None``): the N-return map form — fully
    non-blocking, every task submitted before returning (callers keep
    driver/laziness overlap) — UNLESS locality scheduling is on
    (cfg.sched_w_locality > 0), which auto-selects the streaming form:
    a reduce submitted in lockstep with its partitions' seals carries
    LOCATED deps, so the head's locality term can score it against the
    partitions' actual residency (a reduce submitted before its maps
    ran has nothing to score). ``streaming=True``: maps emit partitions
    through ``num_returns="streaming"`` generators and reduces launch
    as each partition row lands — per-partition seals spread
    object-plane pressure across the map stage instead of one burst per
    map, at the cost of the driver walking the streams (reference:
    hash_shuffle block emission over ObjectRefGenerator).

    Each reduce's map-partition refs are freed eagerly as that reduce
    seals (cfg.data_shuffle_eager_free), so arena fill is bounded by
    in-flight reduces — a 10k-partition shuffle is out-of-core."""
    if reduce_fn is None:
        reduce_fn = _reduce_concat
    if streaming is None:
        streaming = float(cfg.sched_w_locality) > 0
    if streaming:
        return _shuffle_blocks_streaming(
            blocks, num_parts, mode, key_fn, bounds, seed,
            reduce_fn, reduce_args,
        )
    map_refs = [
        _partition_block.options(num_returns=num_parts).remote(
            block,
            num_parts,
            mode,
            key_fn,
            bounds,
            None if seed is None else seed + i,
        )
        for i, block in enumerate(blocks)
    ]
    if num_parts == 1:
        map_refs = [[r] for r in map_refs]
    out = [
        reduce_fn.remote(*reduce_args, *[m[p] for m in map_refs])
        for p in range(num_parts)
    ]
    _watch_eager_free(
        [(out[p], [m[p] for m in map_refs]) for p in range(num_parts)]
    )
    return out


def _shuffle_blocks_streaming(
    blocks, num_parts, mode, key_fn, bounds, seed, reduce_fn, reduce_args
) -> List[Any]:
    gens = [
        _partition_block_stream.options(num_returns="streaming").remote(
            block,
            num_parts,
            mode,
            key_fn,
            bounds,
            None if seed is None else seed + i,
        )
        for i, block in enumerate(blocks)
    ]
    iters = [iter(g) for g in gens]
    last: List[Any] = [None] * len(iters)

    def next_part(i: int):
        try:
            last[i] = next(iters[i])
        except StopIteration:
            # the stream ended early: its final item is the map task's
            # sealed error — hand that ref to the reduce so the failure
            # surfaces as a TaskError on get(), like the N-return form
            if last[i] is None:
                raise RuntimeError(
                    f"shuffle map {i} produced no partitions"
                ) from None
        return last[i]

    out = []
    pairs = []
    for _p in range(num_parts):
        # generators yield in partition order: one lockstep row across
        # all maps unlocks reduce _p
        parts_p = [next_part(i) for i in range(len(iters))]
        out.append(reduce_fn.remote(*reduce_args, *parts_p))
        pairs.append((out[-1], list(parts_p)))
    _watch_eager_free(pairs)
    return out


def sample_bounds(
    blocks: List[List[Any]],
    num_parts: int,
    key_fn: Optional[Callable],
    samples_per_block: int = 64,
) -> List[Any]:
    """Range-partition boundaries from per-block samples (the reference's
    sort sampling stage, planner/exchange/sort_task_spec.py)."""

    @ray_tpu.remote
    def sample(block):
        from ray_tpu.data.block import block_rows

        keys = [key_fn(r) if key_fn else r for r in block_rows(block)]
        if len(keys) <= samples_per_block:
            return keys
        idx = np.random.default_rng(0).choice(
            len(keys), samples_per_block, replace=False
        )
        return [keys[i] for i in idx]

    all_keys = sorted(
        k
        for block_keys in ray_tpu.get([sample.remote(b) for b in blocks])
        for k in block_keys
    )
    if not all_keys:
        return []
    step = max(1, len(all_keys) // num_parts)
    return [all_keys[i] for i in range(step, len(all_keys), step)][: num_parts - 1]
