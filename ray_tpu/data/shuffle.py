"""Distributed shuffle primitives: hash/range partition + reduce.

The analog of the reference's hash-shuffle operator family
(/root/reference/python/ray/data/_internal/execution/operators/
hash_shuffle.py and planner/exchange/): a map stage partitions every
block by key (hash or sampled range), a reduce stage gathers one
partition id from all map outputs — all as framework tasks over the
object plane, so shuffles ride the same lease/object machinery as any
other workload.
"""
from __future__ import annotations

import hashlib
from typing import Any, Callable, List, Optional

import numpy as np

import ray_tpu


def _stable_hash(value: Any) -> int:
    """Deterministic across worker processes (builtin hash() is salted) and
    type-insensitive for numerics: 1, 1.0, and np.float64(1.0) must land in
    the same partition or groupby/join silently split equal keys."""
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, (int, np.integer)):
        return int(value)
    if isinstance(value, (float, np.floating)):
        f = float(value)
        if f.is_integer():
            return int(f)
        data = repr(f).encode()
    elif isinstance(value, (str, np.str_)):
        data = str(value).encode()
    else:
        data = repr(value).encode()
    return int.from_bytes(hashlib.md5(data).digest()[:8], "little")


def _compute_parts(
    block: List[Any],
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
) -> List[List[Any]]:
    """Split one block into num_parts lists (shared by both map tasks)."""
    from .block import block_rows

    block = block_rows(block)  # hash/range partitioning is row-wise
    parts: List[List[Any]] = [[] for _ in range(num_parts)]
    if mode == "random":
        rng = np.random.default_rng(seed)
        dest = rng.integers(0, num_parts, size=len(block))
        for row, d in zip(block, dest):
            parts[int(d)].append(row)
    elif mode == "hash":
        for row in block:
            key = key_fn(row) if key_fn else row
            parts[_stable_hash(key) % num_parts].append(row)
    elif mode == "range":
        for row in block:
            key = key_fn(row) if key_fn else row
            lo, hi = 0, len(bounds)  # first bound > key
            while lo < hi:
                mid = (lo + hi) // 2
                if bounds[mid] <= key:
                    lo = mid + 1
                else:
                    hi = mid
            parts[lo].append(row)
    else:
        raise ValueError(f"unknown partition mode {mode}")
    return parts


@ray_tpu.remote
def _partition_block(
    block: List[Any],
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
) -> tuple:
    """Map side (N-return form): split one block into num_parts lists."""
    parts = _compute_parts(block, num_parts, mode, key_fn, bounds, seed)
    if num_parts == 1:
        return parts[0]  # num_returns=1 -> single (unwrapped) return value
    return tuple(parts)


@ray_tpu.remote
def _partition_block_stream(
    block: List[Any],
    num_parts: int,
    mode: str,
    key_fn: Optional[Callable],
    bounds: Optional[List[Any]],
    seed: Optional[int],
):
    """Map side (streaming form): yield partitions in index order.

    Each partition seals as its own object the moment it is yielded
    (num_returns="streaming"), so reduce p launches as soon as every map
    has emitted its p-th partition — the reference's streaming block
    emission for shuffles (hash_shuffle.py rides ObjectRefGenerator the
    same way) instead of waiting for whole map tasks."""
    for part in _compute_parts(block, num_parts, mode, key_fn, bounds, seed):
        yield part


@ray_tpu.remote
def _reduce_concat(*parts: List[Any]) -> List[Any]:
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    return out


@ray_tpu.remote
def _reduce_shuffled(seed: int, *parts: List[Any]) -> List[Any]:
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    rng = np.random.default_rng(seed)
    return [out[i] for i in rng.permutation(len(out))]


@ray_tpu.remote
def _reduce_sorted(key_fn: Optional[Callable], descending: bool, *parts) -> List[Any]:
    out: List[Any] = []
    for p in parts:
        out.extend(p)
    out.sort(key=key_fn, reverse=descending)
    return out


def shuffle_blocks(
    blocks: List[List[Any]],
    num_parts: int,
    *,
    mode: str = "hash",
    key_fn: Optional[Callable] = None,
    bounds: Optional[List[Any]] = None,
    seed: Optional[int] = None,
    reduce_fn=None,
    reduce_args: tuple = (),
    streaming: bool = False,
) -> List[Any]:
    """Run the two-stage shuffle; returns one ObjectRef per output part.

    Default: the N-return map form — fully non-blocking, every task
    submitted before returning (callers keep driver/laziness overlap).
    ``streaming=True``: maps emit partitions through
    ``num_returns="streaming"`` generators and reduces launch in lockstep
    as each partition row lands — per-partition seals spread object-plane
    pressure across the map stage instead of one burst per map, at the
    cost of the driver walking the streams (reference: hash_shuffle block
    emission over ObjectRefGenerator)."""
    if reduce_fn is None:
        reduce_fn = _reduce_concat
    if streaming:
        return _shuffle_blocks_streaming(
            blocks, num_parts, mode, key_fn, bounds, seed,
            reduce_fn, reduce_args,
        )
    map_refs = [
        _partition_block.options(num_returns=num_parts).remote(
            block,
            num_parts,
            mode,
            key_fn,
            bounds,
            None if seed is None else seed + i,
        )
        for i, block in enumerate(blocks)
    ]
    if num_parts == 1:
        map_refs = [[r] for r in map_refs]
    return [
        reduce_fn.remote(*reduce_args, *[m[p] for m in map_refs])
        for p in range(num_parts)
    ]


def _shuffle_blocks_streaming(
    blocks, num_parts, mode, key_fn, bounds, seed, reduce_fn, reduce_args
) -> List[Any]:
    gens = [
        _partition_block_stream.options(num_returns="streaming").remote(
            block,
            num_parts,
            mode,
            key_fn,
            bounds,
            None if seed is None else seed + i,
        )
        for i, block in enumerate(blocks)
    ]
    iters = [iter(g) for g in gens]
    last: List[Any] = [None] * len(iters)

    def next_part(i: int):
        try:
            last[i] = next(iters[i])
        except StopIteration:
            # the stream ended early: its final item is the map task's
            # sealed error — hand that ref to the reduce so the failure
            # surfaces as a TaskError on get(), like the N-return form
            if last[i] is None:
                raise RuntimeError(
                    f"shuffle map {i} produced no partitions"
                ) from None
        return last[i]

    out = []
    for _p in range(num_parts):
        # generators yield in partition order: one lockstep row across
        # all maps unlocks reduce _p
        parts_p = [next_part(i) for i in range(len(iters))]
        out.append(reduce_fn.remote(*reduce_args, *parts_p))
    return out


def sample_bounds(
    blocks: List[List[Any]],
    num_parts: int,
    key_fn: Optional[Callable],
    samples_per_block: int = 64,
) -> List[Any]:
    """Range-partition boundaries from per-block samples (the reference's
    sort sampling stage, planner/exchange/sort_task_spec.py)."""

    @ray_tpu.remote
    def sample(block):
        from ray_tpu.data.block import block_rows

        keys = [key_fn(r) if key_fn else r for r in block_rows(block)]
        if len(keys) <= samples_per_block:
            return keys
        idx = np.random.default_rng(0).choice(
            len(keys), samples_per_block, replace=False
        )
        return [keys[i] for i in idx]

    all_keys = sorted(
        k
        for block_keys in ray_tpu.get([sample.remote(b) for b in blocks])
        for k in block_keys
    )
    if not all_keys:
        return []
    step = max(1, len(all_keys) // num_parts)
    return [all_keys[i] for i in range(step, len(all_keys), step)][: num_parts - 1]
