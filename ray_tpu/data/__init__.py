"""ray_tpu.data — streaming datasets over object-store blocks.

Analog of Ray Data (/root/reference/python/ray/data/): a Dataset is a lazy
logical plan over blocks; consumption runs a streaming executor that maps
blocks through the operator chain as parallel tasks with bounded in-flight
work (backpressure), blocks flowing through the object store as ObjectRefs
(streaming_executor.py:77 shape, collapsed to a fused operator chain).
"""
from .dataset import (  # noqa: F401
    Dataset,
    GroupedData,
    from_items,
    from_numpy,
    from_numpy_blocks,
    range_,
)
from .execution import ActorPoolStrategy, actors  # noqa: F401
from .io import (  # noqa: F401
    from_numpy,
    from_pandas,
    read_csv,
    read_json,
    read_parquet,
    to_pandas,
    write_csv,
    write_parquet,
)

range = range_  # ray_tpu.data.range(n) parity with ray.data.range
